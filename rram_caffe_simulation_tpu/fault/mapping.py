"""Tiled crossbar mapping: weight matrices split across fault-independent
crossbar tiles (ROADMAP item 1; the mapping axis CIM-Explorer, arXiv
2505.14303, sweeps and the multi-tile layer model NEON, arXiv 2211.05730,
assumes for per-tile ADC readout).

The reference (and this port before ISSUE 11) maps every fault-target
weight matrix onto ONE idealized crossbar: a single fault draw covers
the whole matrix, and the whole analog accumulation is read through one
ADC. Real arrays are bounded (128x128 .. 512x512 cells), so an
ImageNet-class FC layer spans MANY physical tiles, and that changes the
physics in two ways this module models:

1. **Fault independence per tile** — each physical array is its own
   die area with its own defect/endurance statistics, so every tile of
   a layer gets an INDEPENDENT fault draw: the per-parameter draw key
   is folded per (layer, tile) in tile-major order, making any tile
   grid reproducible from (seed, spec) alone. A 1x1 grid takes the
   unfolded legacy key path and is **byte-identical** to the untiled
   draw (CI-guarded by scripts/check_tiled_mapping.py).

2. **Per-tile ADC partial sums** — the analog MAC happens inside one
   tile; crossing tiles means going through that tile's ADC and
   accumulating DIGITALLY. The effective read of a tiled layer is
   ``y[:, jt] = sum_kt quantize_ste(x[:, kt] @ w_eff[kt, jt])`` —
   `quantize_ste` applied per tile-column partial product before the
   K-tile summation, on both the pure-JAX path and the Pallas kernel
   (fault/hw_aware.py, where the kernel's (j, k) block grid IS the
   tile grid).

`TileSpec` is the canonical selection object (the PR 10 `FaultSpec`
shape: parse / canonical string / equality by canonical form), pinned
end to end: `Solver(tile_spec=)` / proto `rram_forward.tiles` /
`caffe_cli --tiles`, sweep checkpoint meta (v6 — restore refuses a
mismatch, v1-v5 upgrade as the implicit default), serve admission, the
co-design "tiles" axis, and the observe layer's `fault.per_tile`
census.

Spec syntax (canonical forms shown):

- ``"1x1"`` — the default: one tile per weight matrix, byte-identical
  to the untiled program.
- ``"GRxGC"`` (grid form, e.g. ``"2x4"``) — split every fault-target
  2-D weight into at most GR x GC tiles (ceil-divided cell blocks over
  the STORED dims; a matrix smaller than the grid clamps to one cell
  tile minimum, so every tile is non-empty).
- ``"cells=RxC"`` (physical form, e.g. ``"cells=256x256"``) — tiles of
  at most R x C cells, the CIM-Explorer array-size axis; the per-layer
  tile GRID is auto-derived as (ceil(d0/R), ceil(d1/C)).

Tiles are defined over the STORED 2-D weight shape (Caffe layout) for
FC params; the consuming layer maps them onto the crossbar (K, N) view
through its own `transpose` flag. Conv kernels (stored >2-D, Caffe
OIHW `(C_out, C_in/g, kh, kw)`) map onto the crossbar through their
im2col view `(K, N) = (C_in/g*kh*kw, C_out)` — the exact GEMM view
`lax.conv_general_dilated_patches` multiplies against (ISSUE 18) — so
their TileSpec geometry, per-tile draws, census, and wear telemetry
are all defined over `im2col_shape(stored)` (`to_im2col` /
`from_im2col` are the exact reshape bijections between the two
layouts). 1-D fault targets (biases) always resolve to a single tile —
they are not crossbar matrices.

This module keeps its parse/geometry layer dependency-light (pure
Python) so analysis tooling — fault/codesign.py, the serve admission
check, summarize — can canonicalize specs without importing JAX; the
draw/census helpers import jax lazily.
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

#: hard cap on tiles per layer: a census/draw loop is unrolled per tile
#: at trace time, so a pathological spec (e.g. grid 512x512 on a small
#: matrix is clamped anyway, but cells=1x1 on fc6 would be ~100M tiles)
#: must fail loudly instead of hanging the tracer
MAX_TILES_PER_LAYER = 4096

_GRID_RE = re.compile(r"^(\d+)x(\d+)$")
_CELLS_RE = re.compile(r"^cells=(\d+)x(\d+)$")

#: the canonical spec of every untiled program (and of pre-v6 sweep
#: checkpoints, which are implicitly untiled)
DEFAULT_TILES = "1x1"


class TileSpec:
    """A parsed tile-mapping selection: `mode` is "grid" (a and b are
    the per-layer tile-grid bounds) or "cells" (a and b are the
    per-tile cell bounds). Compared by `canonical()` — the pin the
    checkpoint meta / serve admission / co-design axis carry."""

    def __init__(self, mode: str, a: int, b: int):
        if mode not in ("grid", "cells"):
            raise ValueError(f"unknown TileSpec mode {mode!r}")
        a, b = int(a), int(b)
        if a < 1 or b < 1:
            raise ValueError(
                f"TileSpec dims must be >= 1, got {a}x{b}")
        if mode == "grid" and a * b > MAX_TILES_PER_LAYER:
            raise ValueError(
                f"TileSpec grid {a}x{b} exceeds {MAX_TILES_PER_LAYER} "
                "tiles per layer (the per-tile draw/census unrolls at "
                "trace time)")
        self.mode = mode
        self.a = a
        self.b = b

    # --- parsing / canonical form -------------------------------------
    @classmethod
    def parse(cls, text) -> "TileSpec":
        if isinstance(text, TileSpec):
            return text
        if text is None or not str(text).strip():
            text = DEFAULT_TILES
        text = str(text).strip().lower()
        m = _GRID_RE.match(text)
        if m:
            return cls("grid", int(m.group(1)), int(m.group(2)))
        m = _CELLS_RE.match(text)
        if m:
            return cls("cells", int(m.group(1)), int(m.group(2)))
        raise ValueError(
            f"bad tile spec {text!r}: expected 'GRxGC' (a per-layer "
            "tile grid, e.g. '2x4'; '1x1' = untiled) or 'cells=RxC' "
            "(cells per tile, e.g. 'cells=256x256')")

    def canonical(self) -> str:
        if self.mode == "cells":
            return f"cells={self.a}x{self.b}"
        return f"{self.a}x{self.b}"

    @property
    def is_default(self) -> bool:
        """True for the 1x1 grid — every layer a single tile, the
        untiled byte-identical program."""
        return self.mode == "grid" and self.a == 1 and self.b == 1

    # --- per-layer geometry -------------------------------------------
    def tile_dims(self, shape) -> Tuple[int, int]:
        """Cells per tile (tr, tc) over the crossbar-mapped 2-D view of
        a stored shape: the stored dims for a 2-D matrix, the im2col
        (K, N) view for a >2-D conv kernel. Grid form ceil-divides the
        dims; cells form clamps to the matrix."""
        if len(shape) > 2:
            shape = im2col_shape(shape)
        if len(shape) != 2:
            raise ValueError(
                f"tile_dims is defined over >=2-D shapes, got {shape}")
        d0, d1 = int(shape[0]), int(shape[1])
        if self.mode == "cells":
            return min(self.a, d0), min(self.b, d1)
        return -(-d0 // min(self.a, d0)), -(-d1 // min(self.b, d1))

    def grid(self, shape) -> Tuple[int, int]:
        """The effective tile grid (gr, gc) for a stored shape: always
        derived from `tile_dims` (so grid-form requests larger than the
        matrix clamp down and every tile is non-empty). >2-D conv
        kernels tile over their im2col (K, N) view; 1-D shapes are a
        single tile by definition."""
        if len(shape) > 2:
            shape = im2col_shape(shape)
        if len(shape) != 2:
            return (1, 1)
        tr, tc = self.tile_dims(shape)
        gr = -(-int(shape[0]) // tr)
        gc = -(-int(shape[1]) // tc)
        if gr * gc > MAX_TILES_PER_LAYER:
            raise ValueError(
                f"tile spec {self.canonical()!r} maps shape "
                f"{tuple(shape)} onto {gr}x{gc} = {gr * gc} tiles, "
                f"over the {MAX_TILES_PER_LAYER}-tile per-layer cap "
                "(the per-tile draw/census unrolls at trace time); "
                "use bigger tiles")
        return gr, gc

    def n_tiles(self, shape) -> int:
        gr, gc = self.grid(shape)
        return gr * gc

    def bounds(self, shape) -> Tuple[List[Tuple[int, int]],
                                     List[Tuple[int, int]]]:
        """([row (lo, hi)...], [col (lo, hi)...]) cell-block boundaries
        over the crossbar-mapped 2-D view (the stored dims for a 2-D
        shape, the im2col (K, N) view for a >2-D conv kernel),
        tile-major (row blocks outer)."""
        if len(shape) > 2:
            shape = im2col_shape(shape)
        tr, tc = self.tile_dims(shape)
        return (split_bounds(int(shape[0]), tr),
                split_bounds(int(shape[1]), tc))

    def tile_slices(self, shape):
        """Yield (tile_index, (r0, r1, c0, c1)) in tile-major order —
        the ONE definition of tile enumeration the draw fold, the
        census, and the kernels share (tile_index is what the draw key
        is folded by)."""
        rb, cb = self.bounds(shape)
        t = 0
        for (r0, r1) in rb:
            for (c0, c1) in cb:
                yield t, (r0, r1, c0, c1)
                t += 1

    def __eq__(self, other):
        return (isinstance(other, TileSpec)
                and self.canonical() == other.canonical())

    def __hash__(self):
        return hash(self.canonical())

    def __repr__(self):
        return f"TileSpec({self.canonical()!r})"


def split_bounds(n: int, t: int) -> List[Tuple[int, int]]:
    """Ceil-split [0, n) into blocks of at most t cells: the last block
    may be smaller, every block is non-empty."""
    return [(lo, min(n, lo + t)) for lo in range(0, n, t)]


def canonical(text) -> str:
    """Parse-and-normalize a spec string (the serve-admission /
    co-design comparison helper)."""
    return TileSpec.parse(text).canonical()


# ---------------------------------------------------------------------------
# the conv im2col crossbar view (ISSUE 18)
#
# A stored conv kernel (Caffe OIHW, (C_out, C_in/g, kh, kw)) reads on
# the crossbar as the im2col GEMM operand: column j of the (K, N) view
# is output filter j flattened over (C_in/g, kh, kw) — the exact matrix
# `lax.conv_general_dilated_patches` output rows multiply against. All
# tile geometry / draws / census for >2-D fault targets are defined
# over this view; the bijections below are pure reshapes (no copy
# semantics beyond layout), so `from_im2col(to_im2col(w), w.shape)` is
# byte-exact.

def im2col_shape(shape) -> Tuple[int, int]:
    """(K, N) im2col crossbar view dims of a stored >2-D conv kernel
    shape: K = prod(shape[1:]) patch features, N = shape[0] output
    channels."""
    if len(shape) <= 2:
        raise ValueError(
            f"im2col_shape is defined over >2-D conv kernels, "
            f"got {tuple(shape)}")
    k = 1
    for d in shape[1:]:
        k *= int(d)
    return (k, int(shape[0]))


def crossbar_view_shape(shape) -> Tuple[int, ...]:
    """The 2-D shape TileSpec geometry is defined over: the stored
    shape for <=2-D params, the im2col (K, N) view for conv kernels."""
    if len(shape) > 2:
        return im2col_shape(shape)
    return tuple(int(d) for d in shape)


def to_im2col(arr, param_ndim=None):
    """Reshape a stored conv kernel array (..., C_out, C_in/g, kh, kw)
    to its (..., K, N) im2col crossbar view. `param_ndim` is the
    trailing stored rank (default: all of `arr.ndim`); leading config
    axes ride through untouched."""
    nd = arr.ndim if param_ndim is None else int(param_ndim)
    lead = tuple(arr.shape[:arr.ndim - nd])
    n = int(arr.shape[arr.ndim - nd])
    return arr.reshape(lead + (n, -1)).swapaxes(-1, -2)


def from_im2col(view, shape):
    """Inverse of `to_im2col`: a (..., K, N) im2col view back to the
    stored conv kernel shape (leading axes preserved)."""
    shape = tuple(int(d) for d in shape)
    lead = tuple(view.shape[:view.ndim - 2])
    return view.swapaxes(-1, -2).reshape(lead + shape)


# ---------------------------------------------------------------------------
# implicit im2col: static address plans over the raw NCHW activation
#
# The patch matrix row m = ((n*OH)+oh)*OW + ow, column kk =
# (c*kh + r)*kw + s of a 2-D conv reads ONE element of the spatially
# zero-padded activation, at flat offset
#
#   idx[m, kk] = row_base[m] + col_off[kk]
#
# because the address decomposes ADDITIVELY: the patch origin
# (n, oh*sh, ow*sw) contributes row_base, the in-patch offset
# (c, r*dh, s*dw) contributes col_off. Two small int32 vectors (M and
# K entries) therefore address the whole (M, K) operand — the kernel
# (or a jax-engine slab closure) gathers any (bm, bk) block as
# xflat[row_base[i0:i1, None] + col_off[None, k0:k1]] without the
# flattened patch matrix ever existing in HBM. Padding is plain
# zero-padding of the activation, so gathered values are bit-identical
# to `lax.conv_general_dilated_patches` output (an exact gather at
# Precision.HIGHEST) — the hinge of the premat/implicit parity
# contract in fault/hw_aware.py.

def conv_geom(kernel, stride, pad, dilation) -> Tuple[int, ...]:
    """Canonical static-geometry tuple (kh, kw, sh, sw, ph, pw, dh, dw)
    of a 2-D conv — hashable, so it can key the lru_cached custom_vmap
    seam in fault/hw_aware.py. Raises for non-2-D spatial geometry
    (the caller falls back to premat, loudly)."""
    if len(kernel) != 2 or len(stride) != 2 or len(pad) != 2 \
            or len(dilation) != 2:
        raise ValueError(
            f"implicit im2col needs 2-D spatial geometry, got "
            f"kernel={tuple(kernel)} stride={tuple(stride)} "
            f"pad={tuple(pad)} dilation={tuple(dilation)}")
    return (int(kernel[0]), int(kernel[1]), int(stride[0]), int(stride[1]),
            int(pad[0]), int(pad[1]), int(dilation[0]), int(dilation[1]))


def im2col_index_plan(x_shape, geom):
    """Precomputed implicit-im2col address plan for an NCHW activation
    of static shape `x_shape` under `conv_geom` tuple `geom`.

    Returns ``(row_base, col_off, m, k, padded_shape)``: int32 numpy
    vectors of length M = N*OH*OW and K = C*kh*kw holding the additive
    flat-offset decomposition above, the logical operand dims, and the
    (N, C, H+2ph, W+2pw) shape the activation must be zero-padded to
    before flattening. Pure numpy — runs at trace time, never inside
    the jaxpr."""
    import numpy as np

    n, c, h, w = (int(d) for d in x_shape)
    kh, kw, sh, sw, ph, pw, dh, dw = geom
    hp, wp = h + 2 * ph, w + 2 * pw
    oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
    if oh < 1 or ow < 1:
        raise ValueError(
            f"implicit im2col: empty output window for x={tuple(x_shape)} "
            f"geom={geom}")
    base_n = np.arange(n, dtype=np.int64) * (c * hp * wp)
    base_oh = np.arange(oh, dtype=np.int64) * (sh * wp)
    base_ow = np.arange(ow, dtype=np.int64) * sw
    row_base = (base_n[:, None, None] + base_oh[None, :, None]
                + base_ow[None, None, :]).reshape(-1)
    off_c = np.arange(c, dtype=np.int64) * (hp * wp)
    off_r = np.arange(kh, dtype=np.int64) * (dh * wp)
    off_s = np.arange(kw, dtype=np.int64) * dw
    col_off = (off_c[:, None, None] + off_r[None, :, None]
               + off_s[None, None, :]).reshape(-1)
    if int(row_base[-1] + col_off[-1]) >= n * c * hp * wp:
        raise AssertionError("implicit im2col plan addresses out of range")
    return (row_base.astype(np.int32), col_off.astype(np.int32),
            n * oh * ow, c * kh * kw, (n, c, hp, wp))


def pad_activation_flat(x, geom):
    """Spatially zero-pad an NCHW activation per `geom` and flatten the
    trailing 4 dims — the only array the implicit-im2col gather reads.
    Leading config axes ride through (a (C, N, Cin, H, W) batch flattens
    to (C, F)). jnp, so it traces; padding with exact zeros keeps
    gathered conv-halo values bit-identical to the patches extraction."""
    import jax.numpy as jnp

    ph, pw = geom[4], geom[5]
    widths = [(0, 0)] * (x.ndim - 2) + [(ph, ph), (pw, pw)]
    return jnp.pad(x, widths).reshape(x.shape[:x.ndim - 4] + (-1,))


def conv_patch_rows(x, geom):
    """Materialized (N*OH*OW, C*kh*kw) im2col patch rows of an NCHW
    activation at Precision.HIGHEST — the exact-gather extraction the
    premat operand mode uses (`ops/vision.ConvolutionLayer._patch_rows`)
    and the implicit mode's v1 backward replays so its cotangents stay
    bit-identical to premat's."""
    import jax.numpy as jnp  # noqa: F401  (keeps lazy-jax discipline)
    from jax import lax

    kh, kw, sh, sw, ph, pw, dh, dw = geom
    p = lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=(sh, sw),
        padding=[(ph, ph), (pw, pw)], rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=lax.Precision.HIGHEST)
    n_, f, oh, ow = p.shape
    return p.transpose(0, 2, 3, 1).reshape(n_ * oh * ow, f)


# ---------------------------------------------------------------------------
# per-(layer, tile) independent draws

def tiled_draw(key, shape, tiles, draw_fn):
    """Assemble one parameter's draw tile by tile: `draw_fn(key, shape)`
    is called once per tile with the key folded by the tile index
    (tile-major, `TileSpec.tile_slices` order), and the blocks are
    concatenated back into the full stored shape — so any tile grid is
    reproducible from (key, spec) alone and tile (i, j)'s cells depend
    only on (key, tile index, tile shape).

    >2-D conv kernels tile over their im2col (K, N) view: the blocks
    are drawn and assembled in view layout (the crossbar's physical
    cell layout), then reshaped back to the STORED shape via
    `from_im2col` — the fault state keeps the stored layout every
    elementwise consumer (Fail, the packed banks, the fused epilogue)
    already handles.

    The single-tile case (tiles None / the default spec / a 1-D
    shape / a matrix one tile covers) calls `draw_fn(key, shape)`
    directly with the UNFOLDED key — byte-identical to the pre-tiling
    draw, which is the 1x1 identity contract the CI guard pins."""
    shape = tuple(int(d) for d in shape)
    grid = ((1, 1) if tiles is None or len(shape) < 2
            else tiles.grid(shape))
    if grid[0] * grid[1] == 1:
        return draw_fn(key, shape)
    import jax
    import jax.numpy as jnp
    rb, cb = tiles.bounds(shape)
    t = 0
    rows = []
    for (r0, r1) in rb:
        blocks = []
        for (c0, c1) in cb:
            blocks.append(draw_fn(jax.random.fold_in(key, t),
                                  (r1 - r0, c1 - c0)))
            t += 1
        rows.append(blocks[0] if len(blocks) == 1
                    else jnp.concatenate(blocks, axis=1))
    out = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)
    return from_im2col(out, shape) if len(shape) > 2 else out


# ---------------------------------------------------------------------------
# tile-resolved fault census (the observe `fault.per_tile` block)

def per_tile_counters(life, stuck, tiles: TileSpec) -> dict:
    """Traced per-tile census reductions for ONE >=2-D fault leaf:
    broken-cell fraction, minimum remaining lifetime, and the stuck-
    value histogram of the BROKEN cells per tile (how many dead cells
    read -1 / 0 / +1 — the spatial defect map per physical array).
    >2-D conv leaves are censused over their im2col (K, N) view (the
    tile layout the draws and the crossbar read use), and the record
    carries the view dims so readers can label the geometry.

    Returns {"grid": i32[2], "broken_frac": f32[T], "life_min": f32[T],
    "stuck_neg"/"stuck_zero"/"stuck_pos": i32[T]} with T = gr * gc in
    tile-major order (plus "view": i32[2] for conv leaves). Under the
    sweep's config vmap each array gains the leading config axis;
    `counters.to_host` listifies them for the metrics record (schema:
    observe/schema.py PER_TILE_FIELDS)."""
    import jax.numpy as jnp
    view = None
    if life.ndim > 2:
        view = im2col_shape(life.shape)
        life = to_im2col(life)
        stuck = to_im2col(stuck)
    gr, gc = tiles.grid(life.shape)
    broken_frac, life_min = [], []
    s_neg, s_zero, s_pos = [], [], []
    for _, (r0, r1, c0, c1) in tiles.tile_slices(life.shape):
        lt = life[r0:r1, c0:c1]
        st = stuck[r0:r1, c0:c1]
        broken = lt <= 0
        broken_frac.append(jnp.mean(broken.astype(jnp.float32)))
        life_min.append(jnp.min(lt).astype(jnp.float32))
        s_neg.append(jnp.sum(broken & (st == -1.0)).astype(jnp.int32))
        s_zero.append(jnp.sum(broken & (st == 0.0)).astype(jnp.int32))
        s_pos.append(jnp.sum(broken & (st == 1.0)).astype(jnp.int32))
    out = {
        "grid": jnp.asarray([gr, gc], jnp.int32),
        "broken_frac": jnp.stack(broken_frac),
        "life_min": jnp.stack(life_min),
        "stuck_neg": jnp.stack(s_neg),
        "stuck_zero": jnp.stack(s_zero),
        "stuck_pos": jnp.stack(s_pos),
    }
    if view is not None:
        out["view"] = jnp.asarray(list(view), jnp.int32)
    return out


# ---------------------------------------------------------------------------
# per-tile wear census (the observe `health` record's sensor core)

def health_tiles(shape, tiles) -> Tuple[Tuple[int, int], list, List[int]]:
    """Tile enumeration for the wear census over one STORED param
    shape: >=2-D shapes follow the TileSpec grid (None / default = one
    tile) — >2-D conv kernels over their im2col (K, N) view, whose
    slices index that view; 1-D fault targets (biases) are a single
    tile by definition. Host-side geometry — returns ((gr, gc),
    [slice tuple or None per tile], [cells per tile]) so the jitted
    census program never has to return static values."""
    if len(shape) >= 2 and tiles is not None and not tiles.is_default:
        grid = tiles.grid(shape)
        sls = [sl for _, sl in tiles.tile_slices(shape)]
        cells = [(r1 - r0) * (c1 - c0) for r0, r1, c0, c1 in sls]
        return grid, sls, cells
    n = 1
    for d in shape:
        n *= int(d)
    return (1, 1), [None], [n]


def _tile_views(arrs, sl, param_ndim):
    """One tile's view of each array (ellipsis slicing, so leading
    config axes ride through untouched)."""
    if sl is None:
        return arrs
    r0, r1, c0, c1 = sl
    if param_ndim == 2:
        return tuple(a[..., r0:r1, c0:c1] for a in arrs)
    return arrs


def log_histogram(x, edges, axes):
    """Histogram counts of `x` over the fixed bin layout every health
    census shares: bin 0 = (-inf, 0] (broken / just-written), bin i =
    (edges[i-1], edges[i]] with an implicit leading edge of 0, last
    bin = beyond the top edge — len(edges) + 2 bins total, stacked on
    a new trailing axis. Pure comparisons + integer sums, so a NumPy
    reimplementation is bit-exact."""
    import jax.numpy as jnp
    thresholds = [0.0] + [float(e) for e in edges]
    idx = sum((x > t).astype(jnp.int32) for t in thresholds)
    return jnp.stack(
        [jnp.sum((idx == b).astype(jnp.int32), axis=axes)
         for b in range(len(thresholds) + 1)], axis=-1)


def per_tile_health(life, stuck, tiles, edges, param_ndim) -> dict:
    """Traced per-tile wear census for ONE lifetime-bearing fault leaf
    (observe/health.py drives it every `health_every` iterations —
    this never runs inside the train step): remaining-lifetime
    histogram over the fixed log-spaced `edges` (log_histogram bin
    layout; bin 0 = broken), broken-cell fraction, mean remaining
    lifetime, and the stuck-value composition of the broken cells.

    `param_ndim` is the STORED param rank (2 = a crossbar matrix
    following the tile grid; >2 = a conv kernel following the grid
    over its im2col (K, N) view — censused in view layout; 1 = one
    tile); leading config axes pass through, so the sweep's
    config-stacked leaves yield per-config vectors. Returns
    {"life_hist": i32[..., T, B],
    "broken_frac"/"life_mean": f32[..., T], "stuck_neg"/"stuck_zero"/
    "stuck_pos": i32[..., T]} in tile-major order, B = len(edges)+2;
    geometry (grid, cells) comes from `health_tiles` host-side."""
    import jax.numpy as jnp
    if param_ndim > 2:
        # conv leaf: census in the im2col crossbar layout the tile
        # grid is defined over (an exact reshape; cells are the same,
        # only their tile membership follows the physical mapping)
        life = to_im2col(life, param_ndim)
        stuck = to_im2col(stuck, param_ndim)
        param_ndim = 2
    shape = life.shape[life.ndim - param_ndim:]
    _, sls, _ = health_tiles(shape, tiles if param_ndim == 2 else None)
    axes = (-2, -1) if param_ndim == 2 else (-1,)
    hist, bfrac, lmean = [], [], []
    s_neg, s_zero, s_pos = [], [], []
    for sl in sls:
        lt, st = _tile_views((life, stuck), sl, param_ndim)
        broken = lt <= 0
        hist.append(log_histogram(lt, edges, axes))
        bfrac.append(jnp.mean(broken.astype(jnp.float32), axis=axes))
        lmean.append(jnp.mean(lt, axis=axes).astype(jnp.float32))
        s_neg.append(jnp.sum((broken & (st == -1.0)).astype(jnp.int32),
                             axis=axes))
        s_zero.append(jnp.sum((broken & (st == 0.0)).astype(jnp.int32),
                              axis=axes))
        s_pos.append(jnp.sum((broken & (st == 1.0)).astype(jnp.int32),
                             axis=axes))
    return {
        "life_hist": jnp.stack(hist, axis=-2),
        "broken_frac": jnp.stack(bfrac, axis=-1),
        "life_mean": jnp.stack(lmean, axis=-1),
        "stuck_neg": jnp.stack(s_neg, axis=-1),
        "stuck_zero": jnp.stack(s_zero, axis=-1),
        "stuck_pos": jnp.stack(s_pos, axis=-1),
    }


def per_tile_ages(age, tiles, edges, param_ndim) -> dict:
    """Traced per-tile drift-age distribution for ONE `drift_age` leaf
    (conductance_drift's health contribution): age histogram over the
    fixed log-spaced `edges` (bin 0 = age <= 0, written this step /
    never drifted), mean and max age per tile. Same tile-major layout,
    im2col conv-view routing, and leading-axis pass-through as
    `per_tile_health`."""
    import jax.numpy as jnp
    if param_ndim > 2:
        age = to_im2col(age, param_ndim)
        param_ndim = 2
    shape = age.shape[age.ndim - param_ndim:]
    _, sls, _ = health_tiles(shape, tiles if param_ndim == 2 else None)
    axes = (-2, -1) if param_ndim == 2 else (-1,)
    hist, amean, amax = [], [], []
    for sl in sls:
        (at,) = _tile_views((age,), sl, param_ndim)
        hist.append(log_histogram(at, edges, axes))
        amean.append(jnp.mean(at, axis=axes).astype(jnp.float32))
        amax.append(jnp.max(at, axis=axes).astype(jnp.float32))
    return {
        "age_hist": jnp.stack(hist, axis=-2),
        "age_mean": jnp.stack(amean, axis=-1),
        "age_max": jnp.stack(amax, axis=-1),
    }


__all__ = [
    "TileSpec", "DEFAULT_TILES", "MAX_TILES_PER_LAYER", "canonical",
    "split_bounds", "im2col_shape", "crossbar_view_shape", "to_im2col",
    "from_im2col", "tiled_draw", "per_tile_counters", "health_tiles",
    "log_histogram", "per_tile_health", "per_tile_ages",
]

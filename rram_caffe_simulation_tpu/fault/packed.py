"""Bit-packed fault-state banks: the per-cell state the sweep reads every
step for every config, at ~2.25 bytes/cell instead of 8.

The f32 engine (engine.py) carries two f32 leaves per cell — a lifetime
and a stuck value in {-1, 0, +1} — plus a derived broken mask. But the
step only ever *compares lifetimes to zero* and *decrements them by the
static write quantum* (`decrement`, the reference's hard-coded batch
size 100, failure_maker.cpp:75), so the full f32 width is dead weight on
the sweep's hottest resident state. The packed layout keeps exactly the
information the transition function uses:

- ``life_q``   — integer *write counters*: ``ceil(lifetime / decrement)``,
  int16 when the operating point's range fits (chosen analytically from
  the mean/std grid at pack time, ``choose_life_dtype``), int32
  otherwise (the paper's 1e8-write endurance point needs int32). One
  write decrements the counter by exactly 1; a cell is broken iff its
  counter is <= 0 — the exact-arithmetic timeline:
  ``life0 - k*decrement <= 0  <=>  ceil(life0/decrement) - k <= 0``.
- ``stuck_bits`` — 2-bit stuck codes (value+1 in {0,1,2}), four cells per
  uint8 lane along the last axis.

There is deliberately NO broken-mask bank: broken is ``life_q <= 0``,
readable from any checkpoint with no extra metadata, and a packed bit
bank would have to be re-derived and re-written on the scan carry every
step — pure waste on exactly the bytes this format exists to shrink.

Timeline caveat at extreme means: the identity above assumes the f32
engine's own subtraction is exact. Below ~2^24 (every int16 operating
point, and the small-lifetime tail that actually breaks in any run) it
is, and the two engines agree bit for bit. At f32 magnitudes whose ulp
exceeds the decrement (the 1e8-write endurance point: ulp(1e8) = 8, so
``life - 100`` rounds every write) the f32 engine accumulates rounding
drift of ~50 writes per million — there the integer counters are the
MORE faithful write-count semantics, not a bit-copy of the reference's
rounding. scripts/check_kernel_parity.py pins the exact regime.

Unpacking a lifetime returns the *mid-bin* value ``(q - 0.5)*decrement``:
every zero-comparison the engine and the mitigation strategies perform
(``> 0`` alive, ``<= 0`` broken, ``< 0`` remap flag) then agrees exactly
with the packed semantics, and ``pack(unpack(q)) == q`` bit-for-bit —
including negative counters from the init distribution's tail. What IS
quantized (once, at pack time) is the sub-decrement remainder of the
initial draw; observe-package lifetime min/mean counters consequently
read at decrement resolution. Fault *transitions* (who breaks when, and
to what stuck value) are exact — scripts/check_kernel_parity.py is the
CI guard.

Packing/unpacking of whole states runs on host at the sweep boundary
(build, checkpoint up/down-grade, lane refill); inside the jitted step
only `fail_packed` (native integer decrement + in-register stuck
unpack) and `unpacked_view` (fused elementwise view for the strategy /
counter consumers) run, so the scan carry — the bytes HBM moves every
iteration — stays packed.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import engine as fault_engine

#: groups a packed fault state carries (remap_slots passes through);
#: broken is derived (life_q <= 0), never stored — see module docstring
PACKED_GROUPS = ("life_q", "stuck_bits")

#: sigma margin when sizing the lifetime counter dtype from the
#: (mean, std) grid: P(|z| > 12) ~ 1e-33 per cell
LIFE_DTYPE_MARGIN = 12.0


def is_packed(state) -> bool:
    """True for a packed fault state (engine.FaultState carries
    "lifetimes"/"stuck"; the packed twin carries the bank groups)."""
    return state is not None and "life_q" in state


def choose_life_dtype(means, stds, decrement: float) -> str:
    """"int16" when every configured (mean, std) pair keeps the
    write-count range inside int16 with a 12-sigma margin, else
    "int32". The choice is analytic (distribution bounds, not the
    sample) so a later lane refill drawing from the same spec can never
    overflow a bank sized here."""
    means = np.atleast_1d(np.asarray(means, np.float64))
    stds = np.atleast_1d(np.asarray(stds, np.float64))
    hi = float(np.max(means + LIFE_DTYPE_MARGIN * stds)) / decrement
    lo = float(np.min(means - LIFE_DTYPE_MARGIN * stds)) / decrement
    if -32000.0 < lo and hi < 32000.0:
        return "int16"
    return "int32"


def make_pack_spec(state: "fault_engine.FaultState", decrement: float,
                   means=None, stds=None, pattern=None) -> dict:
    """The static packing parameters: decrement (write quantum),
    counter dtype, and each leaf's true last-axis length (the packed
    banks pad it to a lane multiple). `state` may be single-config or
    config-stacked — the last axis is the packing axis either way."""
    if means is None:
        means = [float(pattern.mean)] if pattern is not None else [0.0]
    if stds is None:
        stds = [float(pattern.std)] if pattern is not None else [0.0]
    return {
        "decrement": float(decrement),
        "life_dtype": choose_life_dtype(means, stds, decrement),
        "last_dim": {k: int(v.shape[-1])
                     for k, v in state["lifetimes"].items()},
    }


def check_spec_bounds(spec: dict, mean: float, std: float):
    """Raise if a (mean, std) spec could overflow the counter dtype the
    banks were sized with (a self-healing extra-config spec added after
    the int16 choice was frozen)."""
    if spec["life_dtype"] == "int32":
        return
    if choose_life_dtype([mean], [std], spec["decrement"]) != "int16":
        raise ValueError(
            f"fault spec (mean={mean}, std={std}) exceeds the int16 "
            "lifetime banks this packed sweep was built with; build the "
            "runner with this spec present (the dtype choice covers "
            "every known spec) or with packed_state=False")


# ---------------------------------------------------------------------------
# leaf-level pack/unpack

def pack_lifetimes(life, decrement: float, dtype) -> np.ndarray:
    """f32 lifetimes -> integer write counters (host, float64 division
    so the 1e8 operating point's ceil lands on the right side)."""
    q = np.ceil(np.asarray(life, np.float64) / float(decrement))
    info = np.iinfo(np.dtype(dtype))
    if q.size and (q.min() < info.min or q.max() > info.max):
        raise ValueError(
            f"lifetime write-counts [{q.min():.0f}, {q.max():.0f}] do "
            f"not fit {np.dtype(dtype).name} banks")
    # ceil(-0.x) is -0.0; + 0.0 normalizes so the int cast is exact
    return (q + 0.0).astype(dtype)


def unpack_lifetimes(life_q, decrement: float):
    """Integer write counters -> mid-bin f32 lifetimes. Zero
    comparisons (> 0, <= 0, < 0) agree exactly with the counter's, and
    `pack_lifetimes` inverts this exactly (ceil(q - 0.5) == q)."""
    return (life_q.astype(jnp.float32) - 0.5) * float(decrement)


def pack_stuck(stuck) -> np.ndarray:
    """Stuck values in {-1, 0, +1} -> 2-bit codes, 4 cells per uint8
    along the last axis (host-side; stuck never changes in-step)."""
    codes = (np.asarray(stuck) + 1.0).astype(np.uint8)  # {0,1,2}
    pad = -codes.shape[-1] % 4
    if pad:
        codes = np.pad(codes, [(0, 0)] * (codes.ndim - 1) + [(0, pad)])
    codes = codes.reshape(codes.shape[:-1] + (-1, 4))
    shifts = np.arange(4, dtype=np.uint8) * 2
    return np.bitwise_or.reduce(codes << shifts, axis=-1).astype(np.uint8)


def unpack_stuck(bank, last_dim: int):
    """uint8 2-bit banks -> f32 stuck values shaped (..., last_dim).
    jit/vmap-safe: the per-step consumers (fail clamp, crossbar stuck
    tiles) unpack in fused elementwise ops, never storing the wide
    form between steps."""
    parts = [((bank >> (2 * i)) & 3) for i in range(4)]
    codes = jnp.stack(parts, axis=-1).reshape(bank.shape[:-1] + (-1,))
    return codes[..., :last_dim].astype(jnp.float32) - 1.0


# ---------------------------------------------------------------------------
# state-level pack/unpack (host boundary)

def pack_state(state: "fault_engine.FaultState", spec: dict) -> dict:
    """f32 FaultState -> packed banks (host). Extra groups
    (remap_slots) ride along untouched."""
    d, dtype = spec["decrement"], np.dtype(spec["life_dtype"])
    life_q, stuck_bits = {}, {}
    for k, life in state["lifetimes"].items():
        life_q[k] = pack_lifetimes(life, d, dtype)
        stuck_bits[k] = pack_stuck(state["stuck"][k])
    out = {"life_q": life_q, "stuck_bits": stuck_bits}
    for group in state:
        if group not in ("lifetimes", "stuck"):
            out[group] = state[group]
    return out


def unpack_state(packed: dict, spec: dict) -> "fault_engine.FaultState":
    """Packed banks -> f32 FaultState (mid-bin lifetimes; see module
    docstring for what that preserves exactly)."""
    d = spec["decrement"]
    lifetimes = {k: np.asarray(unpack_lifetimes(np.asarray(q), d))
                 for k, q in packed["life_q"].items()}
    stuck = {k: np.asarray(unpack_stuck(np.asarray(b),
                                        spec["last_dim"][k]))
             for k, b in packed["stuck_bits"].items()}
    out: "fault_engine.FaultState" = {"lifetimes": lifetimes,
                                      "stuck": stuck}
    for group in packed:
        if group not in PACKED_GROUPS:
            out[group] = packed[group]
    return out


def convert_flat(arrays: Dict[str, np.ndarray], to_packed: bool,
                 spec: dict) -> Dict[str, np.ndarray]:
    """Convert a flat {"group/key": array} fault mapping (the
    checkpoint / save_fault_states layout, engine.state_to_arrays)
    between formats — the v2<->v3 checkpoint upgrade path."""
    state = fault_engine.state_from_arrays(arrays)
    if to_packed:
        if is_packed(state):
            return dict(arrays)
        state = pack_state(state, spec)
    else:
        if not is_packed(state):
            return dict(arrays)
        state = unpack_state(state, spec)
    return {name: np.asarray(v)
            for name, v in fault_engine.iter_state_leaves(state)}


# ---------------------------------------------------------------------------
# in-step packed engine

def unpacked_view(state: dict, spec: dict) -> "fault_engine.FaultState":
    """A traced f32 view of a packed state for the engine's read-side
    consumers (strategy flag matrices, fault counters, the hw-aware
    broken/stuck masks). Fused elementwise — the view is never a scan
    carry. Mid-bin lifetimes keep every zero-comparison exact."""
    d = spec["decrement"]
    view: "fault_engine.FaultState" = {
        "lifetimes": {k: unpack_lifetimes(q, d)
                      for k, q in state["life_q"].items()},
        "stuck": {k: unpack_stuck(b, spec["last_dim"][k])
                  for k, b in state["stuck_bits"].items()},
    }
    for group in state:
        if group not in PACKED_GROUPS:
            view[group] = state[group]
    return view


def fail_packed(fault_params: Dict[str, jax.Array], state: dict,
                fault_diffs: Dict[str, jax.Array], spec: dict,
                mode: str = "write") -> Tuple[Dict[str, jax.Array], dict]:
    """engine.fail on the packed banks: the write decrement is a native
    integer -1 on the counter bank, the stuck clamp unpacks its 2-bit
    codes in-register, and broken stays derived (`life_q <= 0`) — the
    wide f32 state never exists between steps. Timeline identical to
    engine.fail (see module docstring).

    `mode` is the fault-process decrement policy (fault/processes/):
    "write" (default, the endurance semantics — decrement on written
    steps only), "always" (read disturb — every step is a read), or
    "never" (permanent fault maps — the counter field is static)."""
    new_params, new_life = {}, {}
    for name, data in fault_params.items():
        lq = state["life_q"][name]
        diff = fault_diffs[name]
        alive = lq > 0
        if mode == "write":
            written = jnp.abs(diff) >= fault_engine.EPSILON
            lq2 = jnp.where(alive & written,
                            lq - np.asarray(1, lq.dtype), lq)
        elif mode == "always":
            lq2 = jnp.where(alive, lq - np.asarray(1, lq.dtype), lq)
        elif mode == "never":
            lq2 = lq
        else:
            raise ValueError(f"unknown fail_packed mode {mode!r} "
                             "(expected 'write', 'always', or 'never')")
        broken = lq2 <= 0
        stuck = unpack_stuck(state["stuck_bits"][name],
                             spec["last_dim"][name])
        new_params[name] = jnp.where(broken, stuck.astype(data.dtype),
                                     data)
        new_life[name] = lq2
    return new_params, {**state, "life_q": new_life}


def packed_nbytes(arrays: Dict[str, np.ndarray]) -> int:
    """Total bytes of a flat fault mapping — the checkpoint-shrink
    assertion's measure."""
    return int(sum(np.asarray(v).nbytes for v in arrays.values()))

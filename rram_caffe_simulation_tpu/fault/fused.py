"""Fused ApplyUpdate + Fail epilogue: the SGD weight update and the
packed fault transition as ONE Pallas kernel per fault-target leaf.

The unfused step streams each fault key through three separate HBM
round trips at the tail of every iteration: ApplyUpdate reads
(data, upd) and writes data', then `fail_packed` reads
(data', upd, life_q, stuck_bits) and writes (data'', life_q') — the
packed banks this format exists to shrink are still touched by two
distinct ops. Here the whole tail is one launch: a (data, upd, life_q,
stuck_bits) tile is read into VMEM once, the update subtract, the
counter decrement, the broken comparison, and the in-register 2-bit
stuck unpack all happen on the tile, and (data', life_q') are written
back once — the banks are read-modified-written in VMEM (ROADMAP
item 3 / ISSUE 13 tentpole (2)).

Semantics are EXACTLY the unfused `data - upd` followed by
`fault_packed.fail_packed`: every op is the same elementwise jnp
arithmetic (the stuck unpack calls packed.unpack_stuck itself), so the
fused path is bit-identical to the unfused one on every backend —
`scripts/check_kernel_parity.py` pins losses AND raw bank bytes.

`mode` is the fault-process decrement policy (fault/processes/):
"write" (endurance — decrement on written steps only), "always" (read
disturb — every step is a read), "never" (permanent fault maps).
Which processes fuse is declared by `FaultProcess.fused_mode`
(fault/processes/base.py); a stack the epilogue cannot express (decay
processes mutate values BETWEEN the update and the clamp) falls back
to the unfused path — `ProcessStack.supports_fused_epilogue`.

vmap over all four operands — the sweep's config axis — dispatches to
one config-grid launch; `shard_mesh` additionally runs the dispatch
under `shard_map` over the mesh's "config" axis (hw_aware.
config_shard_map — each shard read-modify-writes only its own config
rows' banks).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import engine as fault_engine
from . import packed as fault_packed

#: fault-process decrement policies the epilogue can express
#: (fault_packed.fail_packed's mode vocabulary)
FUSED_MODES = ("write", "always", "never")


def _epilogue_tile(data, upd, lq, bank, mode: str):
    """One (rows, lanes) tile of the fused tail — the ONE definition of
    the arithmetic, shared by the single and config-batched kernels and
    (transitively, op for op) by the unfused path it must match bit for
    bit: ApplyUpdate's subtract, fail_packed's counter decrement /
    derived broken mask / in-register stuck unpack, the clamp."""
    new = data - upd
    alive = lq > 0
    one = jnp.asarray(1, lq.dtype)
    if mode == "write":
        written = jnp.abs(upd) >= fault_engine.EPSILON
        lq2 = jnp.where(alive & written, lq - one, lq)
    elif mode == "always":
        lq2 = jnp.where(alive, lq - one, lq)
    else:                          # "never": static counter field
        lq2 = lq
    broken = lq2 <= 0
    # the tile is padded to the bank's full 4-cells-per-byte width, so
    # the unpack needs no last_dim slice (padding columns are cut by
    # the caller); unpack_stuck IS the unfused path's unpack
    stuck = fault_packed.unpack_stuck(bank, bank.shape[-1] * 4)
    return jnp.where(broken, stuck.astype(new.dtype), new), lq2


def _make_fused_kernel(mode: str):
    """Elementwise kernel body — one block covers the whole (padded)
    leaf, so `[...]` indexing serves both the single-config (rows,
    lanes) and the config-batched (1, rows, lanes) block shapes."""
    def kernel(data_ref, upd_ref, lq_ref, bank_ref, od_ref, olq_ref):
        od, olq = _epilogue_tile(data_ref[...], upd_ref[...],
                                 lq_ref[...], bank_ref[...], mode)
        od_ref[...] = od
        olq_ref[...] = olq
    return kernel


def _rows(a):
    """Collapse a leaf to 2-D (rows, last): the packing axis is the
    last axis, everything else is rows (biases become one row)."""
    return a.reshape((1, -1) if a.ndim == 1 else (-1, a.shape[-1]))


def _pad_last(a, width: int):
    return jnp.pad(a, ((0, 0),) * (a.ndim - 1)
                   + ((0, width - a.shape[-1]),))


def _fused_call(data, upd, lq, bank, mode: str):
    """Single-config launch: one whole-leaf block (these are per-config
    leaf tiles — at most a few MB, comfortably VMEM-resident)."""
    import jax.experimental.pallas as pl

    shape, L = data.shape, data.shape[-1]
    Lp = bank.shape[-1] * 4
    d2, u2, l2 = (_pad_last(_rows(a), Lp) for a in (data, upd, lq))
    b2 = _rows(bank)
    out = pl.pallas_call(
        _make_fused_kernel(mode),
        out_shape=(jax.ShapeDtypeStruct(d2.shape, data.dtype),
                   jax.ShapeDtypeStruct(l2.shape, lq.dtype)),
        interpret=jax.default_backend() != "tpu",
    )(d2, u2, l2, b2)
    return (out[0][..., :L].reshape(shape),
            out[1][..., :L].reshape(shape))


def _fused_call_batched(data, upd, lq, bank, mode: str):
    """Config-batched launch: grid axis 0 is the config lane, each
    lane's whole leaf one block — one launch updates every lane's
    params and read-modify-writes every lane's banks."""
    import jax.experimental.pallas as pl

    cfg, shape, L = data.shape[0], data.shape, data.shape[-1]
    Lp = bank.shape[-1] * 4
    r3 = lambda a: a.reshape((a.shape[0], 1, -1) if a.ndim == 2
                             else (a.shape[0], -1, a.shape[-1]))
    d3, u3, l3 = (_pad_last(r3(a), Lp) for a in (data, upd, lq))
    b3 = r3(bank)
    spec = lambda a: pl.BlockSpec((1,) + a.shape[1:], lambda c: (c, 0, 0))
    out = pl.pallas_call(
        _make_fused_kernel(mode),
        grid=(cfg,),
        in_specs=[spec(d3), spec(u3), spec(l3), spec(b3)],
        out_specs=(spec(d3), spec(l3)),
        out_shape=(jax.ShapeDtypeStruct(d3.shape, data.dtype),
                   jax.ShapeDtypeStruct(l3.shape, lq.dtype)),
        interpret=jax.default_backend() != "tpu",
    )(d3, u3, l3, b3)
    return (out[0][..., :L].reshape(shape),
            out[1][..., :L].reshape(shape))


@functools.lru_cache(maxsize=None)
def _vmappable_fused(mode: str, shard_mesh=None):
    """The dispatch seam (hw_aware._vmappable_forward's twin): an
    unbatched call is one single-config launch; the sweep's vmap over
    (data, upd, life_q, stuck_bits) collapses into one config-grid
    launch; mixed batching falls back to per-lane launches under
    lax.map. `shard_mesh` wraps the dispatch in shard_map over the
    config axis — each shard read-modify-writes its own rows' banks."""
    import jax.custom_batching

    @jax.custom_batching.custom_vmap
    def fused(data, upd, lq, bank):
        return _fused_call(data, upd, lq, bank, mode)

    @fused.def_vmap
    def _rule(axis_size, in_batched, data, upd, lq, bank):
        db = in_batched[0]

        def dispatch(data, upd, lq, bank):
            if all(in_batched):
                return _fused_call_batched(data, upd, lq, bank, mode)
            from .hw_aware import per_lane_map
            return per_lane_map(
                lambda *lane: _fused_call(*lane, mode),
                (data, upd, lq, bank), in_batched)

        if shard_mesh is not None:
            from jax.sharding import PartitionSpec as P
            from .hw_aware import config_shard_map
            # outputs are config-stacked data/life_q: one leading
            # config dim (already on `data` when it is batched)
            nd = np.ndim(data) + (0 if db else 1) - 1
            cspec = lambda n: P("config", *([None] * n))
            out = config_shard_map(
                dispatch, shard_mesh, (data, upd, lq, bank),
                in_batched, out_specs=(cspec(nd), cspec(nd)))
        else:
            out = dispatch(data, upd, lq, bank)
        return out, (True, True)
    return fused


def fused_update_fail(data, upd, life_q, stuck_bits, mode: str = "write",
                      shard_mesh=None):
    """(data', life_q') = the fused tail of one step for one fault
    leaf: data' = where(broken', stuck, data - upd) with the counter
    bank decremented per `mode` — bit-identical to `data - upd`
    followed by `fault_packed.fail_packed` (module docstring). `data`
    holds the PRE-update values (ApplyUpdate is fused in); `upd` the
    post-strategy update; `life_q`/`stuck_bits` the packed banks.
    vmap over all four = the sweep's config axis; `shard_mesh` (static)
    runs the dispatch sharded over the mesh's "config" axis."""
    if mode not in FUSED_MODES:
        raise ValueError(f"unknown fused epilogue mode {mode!r} "
                         f"(expected one of {FUSED_MODES})")
    return _vmappable_fused(mode, shard_mesh)(data, upd, life_q,
                                              stuck_bits)

"""Hardware-aware forward: crossbar conductance noise + stuck-cell clamp +
ADC quantization injected into the forward pass with straight-through
gradients.

This is the TPU framework's extension beyond the reference (SURVEY §7 build
plan item 3: "differentiable Pallas noise-injection kernel — conductance
variation sigma, ADC/DAC quantization, stuck masks fused into the GEMM —
with custom_vjp straight-through for hardware-aware training"). The
reference only injects faults into STORED weights after the update
(failure_maker.cu:23-40); here every forward READ can additionally see the
analog crossbar's conductance variation, so training converges to
noise-robust weights.

Two implementations with one contract:

- `perturb_weight` / `quantize_ste`: pure JAX, jit/vmap-safe everywhere
  (the Monte-Carlo sweep vmaps them per config). Straight-through is the
  `x + stop_gradient(f(x) - x)` identity, so d(w_eff)/dw == 1 while the
  forward sees the perturbed value.
- `crossbar_matmul`: a fused Pallas TPU kernel computing
  y = x @ where(broken, stuck, quantize(w) * (1 + sigma*eps)) with the
  noise drawn IN-KERNEL (pltpu PRNG + Box-Muller) per weight tile and
  the optional `q_bits` weight quantization (the ADC/DAC-grid operating
  point, same symmetric-uniform formula as `quantize_ste`) applied to
  the VMEM tile — neither the noisy nor the quantized weight matrix
  ever materializes in HBM. custom_vjp backward uses the CLEAN masked
  weights (noise and quantization treated as forward-only
  perturbations, the standard QAT straight-through choice); with
  sigma == 0 and q_bits == 0 forward and backward match the pure path
  exactly.

ENGINE MATRIX — the single source for the `hw_engine` selection
(referenced by core/registry.py `LayerContext.crossbar` and
`Solver.make_train_step`; mirrors the reference's Caffe-vs-cuDNN engine
choice, layer_factory.cpp:38):

  ==========  ================================  ==============================
  hw_engine   single config (Solver)            Monte-Carlo sweep (SweepRunner)
  ==========  ================================  ==============================
  "jax"       perturb_weight + quantize_ste     same, vmapped per config —
              (pure JAX; vmap/GSPMD-safe        the semantic REFERENCE path
              everywhere)                       and the sweep default
  "pallas"    fused crossbar_matmul kernel      config-batched kernel: the
              (noise + quantize drawn/applied   vmap over (w, broken, stuck,
              in VMEM)                          seed) dispatches to ONE
                                                (config, m, n, k)-grid launch
                                                covering every lane
  "auto"      pallas on the TPU backend,        jax (sweeps opt in to pallas
              jax elsewhere                     explicitly via
                                                SweepRunner(engine=...))
  ==========  ================================  ==============================

Under the mesh (ISSUE 13): a config-ONLY mesh — single-process
multi-chip or a multi-host pod — runs the kernel SHARDED: the
custom_vmap seam wraps the config-batched launch in `shard_map` over
the "config" axis (`crossbar_matmul(..., shard_mesh=mesh)`, set by
the SweepRunner), so each shard issues one launch over its own config
rows with the same per-lane seed words — bit-identical to the
single-process launch (scripts/check_pod_sweep.py). The fused
ApplyUpdate+Fail epilogue (fault/fused.py) shard_maps identically.

Fallbacks (every one loud or semantics-preserving, never silent wrong
answers): under a `compute_dtype` below f32 the kernel still computes
in f32 — the call site (ops/common.py) casts x/w up around the fused
call and the output/cotangents back down, so activations keep the
half-width HBM traffic while the crossbar read keeps f32 numerics
("auto" stays conservative and engages pallas only at native f32; an
explicit hw_engine="pallas" composes with any compute_dtype); the
dp/tp/pp wrappers force "jax", and a sweep mesh with "data"/"model"
axes resolves engine="pallas" to "jax" LOUDLY (one-time stderr line +
the observe `setup` record's `engine_fallback_reason` field — the
kernel has no GSPMD partitioning rule off the config axis); and a
vmap batching pattern that does not batch ALL of w/broken/stuck/seed
(x may be shared or per-config) runs the single-config kernel per lane
under `lax.map` (identical numerics, no fusion win).

Tiled crossbar mapping (fault/mapping.py): a static `tiles =
(bk, bn, adc_bits)` parameter re-shapes the kernel's K/N block grid to
the layer's physical tile grid — each (j, k) block reads its tile's
independent fault slice — and quantizes every tile's analog partial
sum through an adc_bits-wide ADC before the accumulator add (the M
grid pins to one block so the in-kernel dynamic range matches the pure
path's per-call `quantize_ste`). `tiled_crossbar_matmul` is the
pure-path twin, used by the jax engine's layer path and the parity
guard (scripts/check_tiled_mapping.py). `tiles=None` (the default 1x1
spec) builds the exact historical kernels.

Conv layers ride the SAME kernel through their im2col view (ISSUE 18):
ops/vision.py lowers a tiled Convolution to patch rows (M = N*OH*OW,
K = C_in*kh*kw) against the flattened (K, C_out) weight view and calls
`crossbar_matmul` with the tile grid over that view — the operand is
just another (M, K) matrix, so the config-batched launch, custom_vmap
seam, shard_map dispatch, and per-lane seed words all carry over
unchanged. The pure jax engine additionally offers a lazy operand mode
(`tiled_crossbar_matmul_slabs`): per-K-tile patch-slab extraction
inside the tile loop, bit-identical to the pre-materialized operand.

Implicit im2col (ISSUE 19): `crossbar_conv_matmul` is the conv-native
entry — it takes the RAW NCHW activation and gathers each (bm, bk)
operand block INSIDE the kernel from the spatially zero-padded, flat
activation via a precomputed additive address plan
(fault/mapping.py `im2col_index_plan`: block[i, kk] =
xflat[row_base[i] + col_off[kk]], masked by `broadcasted_iota` against
the logical (M, K) bounds so alignment padding stays exactly zero and
cannot raise a tile ADC's abs-max). The flattened patch matrix —
a kh*kw× activation blow-up for overlapping convs — never exists in
HBM; per-lane seed words, per-tile ADC accumulation, the custom_vmap
batching seam, and `shard_map` config dispatch are the SAME code paths
as `crossbar_matmul`, so losses and fault banks are bit-identical to
the premat launch (guarded by tests/test_conv_tiles.py and
scripts/check_tiled_mapping.py). v1 backward: cotangents replay the
premat patches-based VJP (`conv_patch_rows` is materialized in the
backward only) — the engine resolution records this note.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def perturb_weight(w, broken, stuck, key, sigma: float):
    """Forward-read value of a crossbar weight array: multiplicative
    Gaussian conductance variation on live cells, stuck value on broken
    ones. Straight-through: gradients pass to `w` unchanged."""
    noisy = w * (1.0 + sigma * jax.random.normal(key, w.shape, w.dtype)) \
        if sigma else w
    w_eff = jnp.where(broken, stuck.astype(w.dtype), noisy)
    return w + jax.lax.stop_gradient(w_eff - w)


def quantize_ste(x, bits: int, max_abs=None):
    """Symmetric uniform quantization (ADC model) with straight-through
    gradients. `max_abs` defaults to the per-call dynamic range."""
    if not bits:
        return x
    if bits < 2:
        # bits == 1 would give zero symmetric levels -> scale = inf -> NaN
        raise ValueError(f"quantize_ste needs bits >= 2, got {bits}")
    if max_abs is None:
        max_abs = jnp.max(jnp.abs(x))
    levels = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(max_abs, 1e-12) / levels
    q = jnp.clip(jnp.round(x / scale), -levels, levels) * scale
    return x + jax.lax.stop_gradient(q - x)


# ---------------------------------------------------------------------------
# Pallas fused kernel

def _q_levels(q_bits: int) -> float:
    """Symmetric quantization level count for a bit width (0 = off);
    the same 2^(bits-1)-1 grid `quantize_ste` uses."""
    if not q_bits:
        return 0.0
    if q_bits < 2:
        raise ValueError(f"crossbar q_bits needs bits >= 2, got {q_bits}")
    return float(2 ** (q_bits - 1) - 1)


def _quantize_tile(w, scale, levels: float):
    """quantize_ste's forward formula on a VMEM tile: `scale` is the
    whole (per-config) weight matrix's max-abs, computed outside the
    kernel (the grid must be uniform across tiles, like the pure path's
    per-call dynamic range)."""
    s = jnp.maximum(scale, 1e-12) / levels
    return jnp.clip(jnp.round(w / s), -levels, levels) * s


def _gauss_tile(shape):
    """In-kernel N(0,1) tile draw (call after `pltpu.prng_seed`): raw
    32-bit PRNG words -> [0,1) by scale + fractional part (proof
    against signed/unsigned interpretation) -> Box-Muller. The ONE
    definition shared by the single-config and config-batched kernels —
    the batched-vs-per-lane bit-exactness contract hangs on these ops
    matching exactly."""
    from jax.experimental.pallas import tpu as pltpu

    def uniform01(s):
        b = pltpu.prng_random_bits(s)
        u = b.astype(jnp.float32) * (1.0 / 4294967296.0)
        return u - jnp.floor(u)

    u1 = jnp.maximum(uniform01(shape), 1e-12)
    u2 = uniform01(shape)
    return jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * np.pi * u2)


def _w_eff(w, broken, stuck, sigma, eps, q_levels, scale):
    """The effective crossbar read of one weight tile — the semantic
    sequence every kernel variant shares: optional ADC-grid
    quantization, forward-only conductance noise (`eps=None` skips the
    multiply: the sigma == 0 sweep builds no PRNG at all), stuck
    clamp. Under the tiled mapping (fault/mapping.py) the (bk, bn)
    block handed in IS one crossbar tile, so `broken`/`stuck` are that
    tile's independent fault slice — the block grid and the tile grid
    are the same object.

    Both perturbations replay the pure path's straight-through
    arithmetic (`base + (f(base) - base)`, quantize_ste /
    perturb_weight) instead of emitting `f(base)` directly: the two
    spellings can differ by an ulp where the subtract-then-add round
    trip rounds, and the engine-parity guards compare bit for bit."""
    if q_levels:
        w = w + (_quantize_tile(w, scale, q_levels) - w)
    noisy = w * (1.0 + sigma * eps) if eps is not None else w
    return w + (jnp.where(broken > 0, stuck, noisy) - w)


def _adc_read(part, adc_levels: float):
    """One tile's analog partial sum through its ADC: quantize_ste's
    forward formula with the tile's own dynamic range (max-abs over the
    whole partial product — under the tiled mapping the M grid is a
    single block, so the in-kernel reduction sees the same values the
    pure path's per-call `quantize_ste` does, bit for bit; zero
    padding cannot raise an abs-max). The `part + (q - part)` shape
    replays quantize_ste's STE arithmetic EXACTLY — emitting `q`
    directly would differ by an ulp on values where the subtract-then-
    add round-trip rounds, and the tiled-mapping CI guard compares the
    engines bit for bit."""
    if not adc_levels:
        return part
    q = _quantize_tile(part, jnp.max(jnp.abs(part)), adc_levels)
    return part + (q - part)


def _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref, sigma, eps,
                q_levels=0.0, scale=None, adc_levels=0.0):
    """One (block, tile) MAC + accumulate. `adc_levels` is the tiled
    mapping's per-tile ADC (fault/mapping.py): the analog partial sum
    of THIS tile is quantized before the digital accumulation across
    the K-tile grid axis — `o_ref` models the digital accumulator, the
    dot models the in-array analog MAC."""
    w_eff = _w_eff(w_ref[:], broken_ref[:], stuck_ref[:], sigma, eps,
                   q_levels, scale)
    part = jnp.dot(x_ref[:], w_eff, preferred_element_type=jnp.float32)
    o_ref[:] += _adc_read(part, adc_levels)


def _make_crossbar_kernel(q_levels: float, adc_levels: float = 0.0):
    """One (bm, bn) output tile, accumulating over the K grid axis; the
    weight tile is quantized + perturbed in VMEM before hitting the MXU.
    The PRNG is seeded per (j, k) tile so every x-tile sees the SAME
    weight noise. `q_levels` is static: 0 builds the exact historical
    kernel signature (no scale input). `adc_levels` is the tiled
    mapping's per-tile ADC on the partial-sum accumulator (see
    `_apply_tile`; the tiled launch pins the M grid to one block so the
    in-block dynamic range is the whole partial product's)."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (seed_ref, scale_ref, x_ref, w_ref, broken_ref, stuck_ref,
             sigma_ref, o_ref) = refs
        else:
            (seed_ref, x_ref, w_ref, broken_ref, stuck_ref, sigma_ref,
             o_ref) = refs
            scale_ref = None
        j = pl.program_id(1)
        k = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        # Seed and tile index are SEPARATE seed words: with a single word
        # `seed + j*nk + k`, seed s+1 tile t would replay seed s tile t+1
        # — sequential Monte-Carlo seeds would share almost all their
        # noise.
        pltpu.prng_seed(seed_ref[0], j * nk + k)
        eps = _gauss_tile(w_ref[:].shape)
        _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref,
                    sigma_ref[0], eps, q_levels,
                    scale_ref[0] if q_levels else None, adc_levels)
    return kernel


def _make_crossbar_kernel_hostnoise(q_levels: float,
                                    adc_levels: float = 0.0):
    """Interpret-mode twin for off-TPU hosts: identical math, but the
    Gaussian draw arrives as an input (pltpu's in-kernel PRNG has no CPU
    interpret lowering)."""
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (scale_ref, x_ref, w_ref, broken_ref, stuck_ref, eps_ref,
             sigma_ref, o_ref) = refs
        else:
            (x_ref, w_ref, broken_ref, stuck_ref, eps_ref, sigma_ref,
             o_ref) = refs
            scale_ref = None

        @pl.when(pl.program_id(2) == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref,
                    sigma_ref[0], eps_ref[:], q_levels,
                    scale_ref[0] if q_levels else None, adc_levels)
    return kernel


def _m_block(m: int) -> int:
    """The single M-block size of a tiled launch: the whole batch in
    one 8-aligned block. ONE definition shared by the kernel launch
    (`_tile_blocks`) and the pure twin (`tiled_crossbar_matmul`) —
    the per-lane bit-exactness contract between the engines
    (scripts/check_tiled_mapping.py) hangs on both padding the dot to
    the identical shape."""
    return max(8, -(-int(m) // 8) * 8)


def _tile_blocks(tiles, m: int):
    """Resolve a static `tiles` kernel parameter — (bk, bn, adc_bits),
    the crossbar-view tile cell dims + the per-tile ADC width
    (fault/mapping.py via ops/common.py) — into pallas launch knobs:
    (bm, bn, bk, adc_levels). The kernel's (j, k) block grid then IS
    the crossbar tile grid, its broken/stuck blocks the per-tile fault
    slices. The M axis is pinned to ONE block (bm >= m, 8-aligned) so
    the per-tile partial product — whose in-block abs-max is the ADC's
    dynamic range — covers the full batch, exactly like the pure
    path's per-call `quantize_ste` range."""
    bk_t, bn_t, adc_bits = tiles
    return _m_block(m), int(bn_t), int(bk_t), _q_levels(int(adc_bits))


def _pallas_forward(x, w, broken, stuck, seed, sigma, q_bits=0,
                    tiles=None, bm=128, bn=128, bk=128):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, kdim = x.shape
    _, n = w.shape
    adc_levels = 0.0
    if tiles is not None:
        bm, bn, bk, adc_levels = _tile_blocks(tiles, m)

    def pad(a, r, c):
        return jnp.pad(a, ((0, -a.shape[0] % r), (0, -a.shape[1] % c)))

    xp = pad(x, bm, bk)
    wp = pad(w, bk, bn)
    bp = pad(broken, bk, bn)
    sp = pad(stuck, bk, bn)
    gm, gk = xp.shape[0] // bm, xp.shape[1] // bk
    gn = wp.shape[1] // bn
    on_tpu = jax.default_backend() == "tpu"
    levels = _q_levels(q_bits)
    # the quantization grid spans the WHOLE weight matrix (quantize_ste's
    # per-call dynamic range), so the max-abs reduction runs outside the
    # tile loop; padding is zeros, so it can ride the padded array
    scale = ([jnp.max(jnp.abs(wp)).reshape(1)] if levels else [])
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale_spec = [smem] if levels else []
    wspec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    common = dict(
        grid=(gm, gn, gk),
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]),
                                       jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _make_crossbar_kernel(levels, adc_levels),
            in_specs=[smem] + scale_spec + [            # seed (+ scale)
                      pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      wspec, wspec, wspec,
                      smem],                            # sigma
            **common,
        )(jnp.asarray([seed], jnp.int32), *scale, xp, wp, bp, sp, sig)
    else:
        eps = jax.random.normal(jax.random.PRNGKey(seed), wp.shape,
                                jnp.float32)
        out = pl.pallas_call(
            _make_crossbar_kernel_hostnoise(levels, adc_levels),
            in_specs=scale_spec + [
                      pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      wspec, wspec, wspec, wspec,
                      smem],
            interpret=True,
            **common,
        )(*scale, xp, wp, bp, sp, eps, sig)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# config-batched sweep kernel: one (config, m, n, k) grid launch forms
# every lane's faulty+noisy+quantized weights in VMEM — the per-lane
# weight matrices never round-trip HBM (ROADMAP item 3 / ISSUE 7 (a))

def _make_batched_kernel(q_levels: float, draw_noise: bool,
                         x_batched: bool, adc_levels: float = 0.0):
    """The config-grid twin of `_make_crossbar_kernel`: grid axis 0 is
    the config lane; each lane is seeded with ITS OWN seed word and the
    SAME (j*nk + k) tile index, so per-lane noise streams are
    bit-identical to per-lane single-config kernel launches — the
    batched-vs-per-lane parity tests compare exactly, not
    statistically. `draw_noise` is static: a sigma == 0 sweep (e.g. the
    pure ternary operating point) skips the Box-Muller draw entirely.
    `x_batched` is static: False streams ONE shared (M, K) input to
    every lane (the genetic-search eval pattern); True gives each lane
    its own input slab (the training sweep pattern — activations differ
    per config because the upstream weights do)."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (seed_ref, scale_ref, x_ref, w_ref, broken_ref, stuck_ref,
             sigma_ref, o_ref) = refs
        else:
            (seed_ref, x_ref, w_ref, broken_ref, stuck_ref, sigma_ref,
             o_ref) = refs
            scale_ref = None
        c = pl.program_id(0)
        j = pl.program_id(2)
        k = pl.program_id(3)
        nk = pl.num_programs(3)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        w = w_ref[0]
        if draw_noise:
            # per-lane seed word + the SAME (j*nk + k) tile index as
            # the single-config kernel -> bit-identical per-lane noise
            pltpu.prng_seed(seed_ref[c], j * nk + k)
            eps = _gauss_tile(w.shape)
        else:
            eps = None
        w_eff = _w_eff(w, broken_ref[0], stuck_ref[0],
                       sigma_ref[0] if draw_noise else None, eps,
                       q_levels, scale_ref[c] if q_levels else None)
        xt = x_ref[0] if x_batched else x_ref[:]
        part = jnp.dot(xt, w_eff, preferred_element_type=jnp.float32)
        o_ref[0] += _adc_read(part, adc_levels)
    return kernel


def _make_batched_kernel_hostnoise(q_levels: float, draw_noise: bool,
                                   x_batched: bool,
                                   adc_levels: float = 0.0):
    """Interpret-mode twin of `_make_batched_kernel` (per-lane Gaussian
    draws arrive as a (config, K, N) input)."""
    import jax.experimental.pallas as pl

    def kernel(*refs):
        refs = list(refs)
        scale_ref = refs.pop(0) if q_levels else None
        x_ref, w_ref, broken_ref, stuck_ref = refs[:4]
        refs = refs[4:]
        eps_ref = refs.pop(0) if draw_noise else None
        sigma_ref, o_ref = refs
        c = pl.program_id(0)

        @pl.when(pl.program_id(3) == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        w_eff = _w_eff(w_ref[0], broken_ref[0], stuck_ref[0],
                       sigma_ref[0] if draw_noise else None,
                       eps_ref[0] if draw_noise else None,
                       q_levels, scale_ref[c] if q_levels else None)
        xt = x_ref[0] if x_batched else x_ref[:]
        part = jnp.dot(xt, w_eff, preferred_element_type=jnp.float32)
        o_ref[0] += _adc_read(part, adc_levels)
    return kernel


def _pallas_forward_batched(x, w, broken, stuck, seeds, sigma, q_bits=0,
                            tiles=None, bm=128, bn=128, bk=128):
    """The config-batched launch: x (M, K) SHARED across lanes or
    (C, M, K) per lane; w/broken/stuck (C, K, N) and seeds (C,) per
    lane; one pallas_call over grid (C, gm, gn, gk). Every lane's
    weight tile is formed in VMEM — per-lane weight matrices never
    materialize in HBM."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cfg = w.shape[0]
    x_batched = x.ndim == 3
    m, kdim = x.shape[-2:]
    n = w.shape[2]
    adc_levels = 0.0
    if tiles is not None:
        bm, bn, bk, adc_levels = _tile_blocks(tiles, m)

    def pad2(a, r, c):
        return jnp.pad(a, ((0, -a.shape[0] % r), (0, -a.shape[1] % c)))

    def pad3(a, r, c):
        return jnp.pad(a, ((0, 0), (0, -a.shape[1] % r),
                           (0, -a.shape[2] % c)))

    xp = pad3(x, bm, bk) if x_batched else pad2(x, bm, bk)
    wp = pad3(w, bk, bn)
    bp = pad3(broken, bk, bn)
    sp = pad3(stuck, bk, bn)
    gm, gk = xp.shape[-2] // bm, xp.shape[-1] // bk
    gn = wp.shape[2] // bn
    on_tpu = jax.default_backend() == "tpu"
    levels = _q_levels(q_bits)
    draw = bool(sigma)
    # per-lane quantization grids (each config trains its own weights,
    # so each lane has its own dynamic range — matching what
    # quantize_ste computes per lane under the pure engine's vmap)
    scale = ([jnp.max(jnp.abs(wp), axis=(1, 2))] if levels else [])
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale_spec = [smem] if levels else []
    xspec = (pl.BlockSpec((1, bm, bk), lambda c, i, j, k: (c, i, k))
             if x_batched
             else pl.BlockSpec((bm, bk), lambda c, i, j, k: (i, k)))
    wspec = pl.BlockSpec((1, bk, bn), lambda c, i, j, k: (c, k, j))
    common = dict(
        grid=(cfg, gm, gn, gk),
        out_specs=pl.BlockSpec((1, bm, bn), lambda c, i, j, k: (c, i, j)),
        out_shape=jax.ShapeDtypeStruct((cfg, xp.shape[-2], wp.shape[2]),
                                       jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _make_batched_kernel(levels, draw, x_batched, adc_levels),
            in_specs=[smem] + scale_spec + [xspec, wspec, wspec, wspec,
                                            smem],
            **common,
        )(jnp.asarray(seeds, jnp.int32), *scale, xp, wp, bp, sp, sig)
    else:
        eps = ([jax.vmap(lambda s: jax.random.normal(
                    jax.random.PRNGKey(s), wp.shape[1:], jnp.float32))(
                        seeds)] if draw else [])
        eps_spec = [wspec] if draw else []
        out = pl.pallas_call(
            _make_batched_kernel_hostnoise(levels, draw, x_batched,
                                           adc_levels),
            in_specs=scale_spec + [xspec, wspec, wspec, wspec]
            + eps_spec + [smem],
            interpret=True,
            **common,
        )(*scale, xp, wp, bp, sp, *eps, sig)
    return out[:, :m, :n]


def config_shard_specs(args, in_batched, axis: str = "config"):
    """PartitionSpecs for a config-batched operand list under the
    sweep's mesh: batched operands shard their leading (config) dim
    over `axis`, unbatched operands replicate. Shared by the crossbar
    seam below and the fused fail+update epilogue (fault/fused.py) —
    ONE definition so every kernel the sweep launches under `shard_map`
    agrees on which rows live where."""
    from jax.sharding import PartitionSpec as P
    return tuple(
        P(axis, *([None] * (np.ndim(a) - 1))) if b
        else P(*([None] * np.ndim(a)))
        for a, b in zip(args, in_batched))


def per_lane_map(fn, args, in_batched):
    """The mixed-batching fallback every config-batched kernel seam
    shares: `lax.map` of the single-lane `fn` over the batched
    operands' rows — unbatched operands stay closure-captured, nothing
    is broadcast-materialized. The row count comes from the operands'
    LOCAL shapes, so the same fallback is correct inside a shard_map
    body (shard-local rows) and outside it (the full axis)."""
    n_rows = [a.shape[0] for a, b in zip(args, in_batched) if b][0]

    def one(i):
        return fn(*[a[i] if b else a
                    for a, b in zip(args, in_batched)])
    return jax.lax.map(one, jnp.arange(n_rows))


def config_shard_map(fn, mesh, args, in_batched, out_specs):
    """Run a config-batched dispatch under `shard_map` over the mesh's
    "config" axis: each shard sees ONLY its local config-row block of
    the batched operands (per-lane seed words ride with the rows, so
    per-lane noise streams are bit-identical to the unsharded launch)
    and issues one local kernel launch — the pod-scale dispatch ROADMAP
    item 3 / ISSUE 13 asks for. `check_rep=False`: the body holds
    pallas_call / lax.map primitives the replication checker cannot
    analyze; the out_specs are the contract."""
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh,
                     in_specs=config_shard_specs(args, in_batched),
                     out_specs=out_specs, check_rep=False)(*args)


@functools.lru_cache(maxsize=None)
def _vmappable_forward(sigma: float, q_bits: int, tiles=None,
                       shard_mesh=None):
    """The engine-dispatch seam between the single-config and the
    config-batched kernel: an unbatched call lowers to the single
    kernel; a vmap over (w, broken, stuck, seed) — the Monte-Carlo
    sweep's config axis, with x either shared (genetic eval) or
    per-config (the training sweep: upstream per-config weights batch
    every activation) — dispatches to ONE config-grid launch; any other
    pattern falls back to per-lane single kernels under lax.map
    (identical numerics, no fusion).

    `shard_mesh` (static, a config-axis jax Mesh or None) is the pod
    dispatch: the whole rule body runs under `shard_map` over the
    mesh's "config" axis, so each shard issues one batched launch over
    its LOCAL config rows — same per-lane seed words, bit-identical to
    the single-process launch (tests/test_sweep_kernels.py +
    scripts/check_pod_sweep.py pin it)."""
    import jax.custom_batching

    @jax.custom_batching.custom_vmap
    def fwd(x, w, broken, stuck, seed):
        return _pallas_forward(x, w, broken, stuck, seed, sigma, q_bits,
                               tiles)

    @fwd.def_vmap
    def _rule(axis_size, in_batched, x, w, broken, stuck, seed):
        wb, bb, sb, seedb = in_batched[1:]   # x may be shared

        def dispatch(x, w, broken, stuck, seed):
            if wb and bb and sb and seedb:
                return _pallas_forward_batched(x, w, broken, stuck,
                                               seed, sigma, q_bits,
                                               tiles)
            # mixed batching (e.g. per-lane fault masks with shared
            # weights): single kernel per lane (`per_lane_map` —
            # identical numerics, no fusion win)
            return per_lane_map(
                lambda *lane: _pallas_forward(*lane, sigma, q_bits,
                                              tiles),
                (x, w, broken, stuck, seed), in_batched)

        if shard_mesh is not None:
            from jax.sharding import PartitionSpec as P
            out = config_shard_map(
                dispatch, shard_mesh, (x, w, broken, stuck, seed),
                in_batched, out_specs=P("config", None, None))
        else:
            out = dispatch(x, w, broken, stuck, seed)
        return out, True
    return fwd


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def crossbar_matmul(x, w, broken, stuck, seed, sigma, q_bits=0,
                    tiles=None, shard_mesh=None):
    """y = x @ where(broken, stuck, quantize(w) * (1 + sigma*eps)) as
    one fused Pallas kernel (noise generated and the optional q_bits
    ADC-grid quantization applied in VMEM, never materialized in HBM).

    x: (M, K) f32; w: (K, N) f32; broken: (K, N) bool; stuck: (K, N) f32;
    seed: int scalar (python or traced); sigma: python float (static);
    q_bits: python int (static; 0 = no quantization, >= 2 = the
    symmetric-uniform grid `quantize_ste` models). Backward is
    straight-through against the CLEAN masked weights.

    `tiles` (static, hashable) engages the tiled crossbar mapping
    (fault/mapping.py): a `(bk, bn, adc_bits)` tuple sets the kernel's
    K/N block grid to the layer's crossbar tile grid — each (j, k)
    block then reads ITS tile's independent fault slice — and
    quantizes every tile's analog partial sum through an
    adc_bits-wide ADC before the accumulator add (the per-tile readout
    NEON assumes; `tiled_crossbar_matmul` is the pure-path twin).
    None (the 1x1 default) builds the exact historical kernel.

    vmap over (w, broken, stuck, seed) — the sweep's config axis, with
    x shared or per-config — dispatches to the config-batched kernel
    (one launch for every lane, per-lane noise streams bit-identical to
    per-lane single launches); see the ENGINE MATRIX in the module
    docstring.

    `shard_mesh` (static, a jax Mesh with a "config" axis, or None) is
    the pod-scale dispatch: the config-batched launch runs under
    `shard_map` over that axis, one local launch per shard over its
    own config rows — bit-identical to the unsharded launch (the
    per-lane seed words travel with the rows). The SweepRunner sets it
    when engine="pallas" runs on a config-sharded mesh."""
    return _vmappable_forward(float(sigma), int(q_bits), tiles,
                              shard_mesh)(
        x, w, broken.astype(jnp.float32), stuck.astype(jnp.float32),
        seed)


def _cm_fwd(x, w, broken, stuck, seed, sigma, q_bits, tiles,
            shard_mesh):
    y = crossbar_matmul(x, w, broken, stuck, seed, sigma, q_bits,
                        tiles, shard_mesh)
    return y, (x, w, broken, stuck)


def _cm_bwd(sigma, q_bits, tiles, shard_mesh, res, g):
    # the per-tile ADC (tiles) is a forward-only perturbation like the
    # output quantize_ste it generalizes: straight-through, so the
    # backward is the SAME clean-masked-weight product either way
    x, w, broken, stuck = res
    wv = w
    if q_bits:
        # dx flows through the values the forward actually used: the
        # ADC-grid weights (quantize_ste's STE differentiates x @ w_eff
        # with w_eff on the grid). dw stays straight-through to the
        # clean master weights.
        wv = _quantize_tile(w, jnp.max(jnp.abs(w)), _q_levels(q_bits))
    w_masked = jnp.where(broken, stuck.astype(w.dtype), wv)
    dx = g @ w_masked.T
    dw = x.T @ g
    # stuck cells take no gradient (their stored value is clamped by the
    # fault engine anyway; matches d/dw of where(broken, stuck, w))
    dw = jnp.where(broken, 0.0, dw)
    return dx, dw, None, None, None


crossbar_matmul.defvjp(_cm_fwd, _cm_bwd)


# ---------------------------------------------------------------------------
# implicit im2col: the conv-native kernel family (ISSUE 19) — each
# (bm, bk) operand block is gathered in-kernel from the raw (padded,
# flattened) NCHW activation via the additive address plan of
# fault/mapping.py; the flattened patch matrix never exists in HBM

def _gather_block(xflat, rb, co, k, bk: int, m: int, kdim: int):
    """Gather one (bm, bk) implicit-im2col operand block: `xflat` is
    the flat zero-padded activation, `rb`/`co` the (bm,)/(bk,) int32
    plan slices, `k` the K-tile program id. The iota masks zero every
    alignment-padding row/column EXACTLY — the premat operand's padding
    is literal zeros, and a nonzero garbage row would raise the tile
    ADC's abs-max dynamic range (`_adc_read`), breaking the
    bit-identity contract. Plan padding entries address offset 0, so
    the gather itself is always in bounds."""
    idx = rb[:, None] + co[None, :]
    xb = jnp.take(xflat, idx)
    bm = rb.shape[0]
    row_ok = jax.lax.broadcasted_iota(jnp.int32, (bm, bk), 0) < m
    col_ok = (k * bk
              + jax.lax.broadcasted_iota(jnp.int32, (bm, bk), 1)) < kdim
    return jnp.where(row_ok & col_ok, xb, 0.0)


def _make_implicit_kernel(q_levels: float, adc_levels: float,
                          m: int, kdim: int, bk: int):
    """Implicit-im2col twin of `_make_crossbar_kernel`: identical
    weight-side math (PRNG seed words, `_w_eff`, per-tile `_adc_read`),
    but the x operand block is gathered in-kernel from the flat padded
    activation instead of arriving as a pre-materialized (bm, bk)
    BlockSpec slab. The M grid is pinned to one block by the tiled
    launch, so grid axis 0 is a singleton."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (seed_ref, scale_ref, x_ref, rb_ref, co_ref, w_ref,
             broken_ref, stuck_ref, sigma_ref, o_ref) = refs
        else:
            (seed_ref, x_ref, rb_ref, co_ref, w_ref, broken_ref,
             stuck_ref, sigma_ref, o_ref) = refs
            scale_ref = None
        j = pl.program_id(1)
        k = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        # same seed-word discipline as the premat kernel: seed and tile
        # index are separate words, tile index is j*nk + k
        pltpu.prng_seed(seed_ref[0], j * nk + k)
        eps = _gauss_tile(w_ref[:].shape)
        xb = _gather_block(x_ref[0], rb_ref[0], co_ref[0], k, bk, m,
                           kdim)
        w_eff = _w_eff(w_ref[:], broken_ref[:], stuck_ref[:],
                       sigma_ref[0], eps, q_levels,
                       scale_ref[0] if q_levels else None)
        part = jnp.dot(xb, w_eff, preferred_element_type=jnp.float32)
        o_ref[:] += _adc_read(part, adc_levels)
    return kernel


def _make_implicit_kernel_hostnoise(q_levels: float, adc_levels: float,
                                    m: int, kdim: int, bk: int):
    """Interpret-mode twin of `_make_implicit_kernel` (the Gaussian
    draw arrives as an input, like every hostnoise kernel)."""
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (scale_ref, x_ref, rb_ref, co_ref, w_ref, broken_ref,
             stuck_ref, eps_ref, sigma_ref, o_ref) = refs
        else:
            (x_ref, rb_ref, co_ref, w_ref, broken_ref, stuck_ref,
             eps_ref, sigma_ref, o_ref) = refs
            scale_ref = None
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        xb = _gather_block(x_ref[0], rb_ref[0], co_ref[0], k, bk, m,
                           kdim)
        w_eff = _w_eff(w_ref[:], broken_ref[:], stuck_ref[:],
                       sigma_ref[0], eps_ref[:], q_levels,
                       scale_ref[0] if q_levels else None)
        part = jnp.dot(xb, w_eff, preferred_element_type=jnp.float32)
        o_ref[:] += _adc_read(part, adc_levels)
    return kernel


def _make_implicit_batched_kernel(q_levels: float, draw_noise: bool,
                                  adc_levels: float, m: int, kdim: int,
                                  bk: int):
    """Config-grid twin of `_make_implicit_kernel` (grid
    (cfg, 1, gn, gk)): per-lane seed words + the same (j*nk + k) tile
    index, per-lane weight/fault/scale rows. Whether x is shared or
    per-lane is decided entirely by the x BlockSpec index map — the
    body always reads `x_ref[0]`, a (F,) flat activation."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (seed_ref, scale_ref, x_ref, rb_ref, co_ref, w_ref,
             broken_ref, stuck_ref, sigma_ref, o_ref) = refs
        else:
            (seed_ref, x_ref, rb_ref, co_ref, w_ref, broken_ref,
             stuck_ref, sigma_ref, o_ref) = refs
            scale_ref = None
        c = pl.program_id(0)
        j = pl.program_id(2)
        k = pl.program_id(3)
        nk = pl.num_programs(3)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        w = w_ref[0]
        if draw_noise:
            pltpu.prng_seed(seed_ref[c], j * nk + k)
            eps = _gauss_tile(w.shape)
        else:
            eps = None
        xb = _gather_block(x_ref[0], rb_ref[0], co_ref[0], k, bk, m,
                           kdim)
        w_eff = _w_eff(w, broken_ref[0], stuck_ref[0],
                       sigma_ref[0] if draw_noise else None, eps,
                       q_levels, scale_ref[c] if q_levels else None)
        part = jnp.dot(xb, w_eff, preferred_element_type=jnp.float32)
        o_ref[0] += _adc_read(part, adc_levels)
    return kernel


def _make_implicit_batched_kernel_hostnoise(q_levels: float,
                                            draw_noise: bool,
                                            adc_levels: float, m: int,
                                            kdim: int, bk: int):
    """Interpret-mode twin of `_make_implicit_batched_kernel`."""
    import jax.experimental.pallas as pl

    def kernel(*refs):
        refs = list(refs)
        scale_ref = refs.pop(0) if q_levels else None
        x_ref, rb_ref, co_ref, w_ref, broken_ref, stuck_ref = refs[:6]
        refs = refs[6:]
        eps_ref = refs.pop(0) if draw_noise else None
        sigma_ref, o_ref = refs
        c = pl.program_id(0)
        k = pl.program_id(3)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        xb = _gather_block(x_ref[0], rb_ref[0], co_ref[0], k, bk, m,
                           kdim)
        w_eff = _w_eff(w_ref[0], broken_ref[0], stuck_ref[0],
                       sigma_ref[0] if draw_noise else None,
                       eps_ref[0] if draw_noise else None,
                       q_levels, scale_ref[c] if q_levels else None)
        part = jnp.dot(xb, w_eff, preferred_element_type=jnp.float32)
        o_ref[0] += _adc_read(part, adc_levels)
    return kernel


def _implicit_plan_arrays(x_shape, geom, tiles):
    """Resolve an implicit launch's static plan + block geometry: the
    padded device-side plan operands — (1, bm) row_base and (1, Kp)
    col_off int32 arrays (plan entries past the logical M/K bounds
    address offset 0 and are zero-masked in-kernel) — plus the logical
    (m, kdim) operand dims and the `_tile_blocks` launch knobs."""
    from .mapping import im2col_index_plan

    rb_np, co_np, m, kdim, _ = im2col_index_plan(x_shape, geom)
    bm, bn, bk, adc_levels = _tile_blocks(tiles, m)
    rb = jnp.asarray(np.pad(rb_np, (0, bm - m)))[None, :]
    co = jnp.asarray(np.pad(co_np, (0, -kdim % bk)))[None, :]
    return rb, co, m, kdim, bm, bn, bk, adc_levels


def _pallas_forward_implicit(x, w, broken, stuck, seed, sigma,
                             q_bits=0, tiles=None, geom=None):
    """Single-config implicit-im2col launch: like `_pallas_forward` on
    the (M, K) patch view, but x arrives as the RAW NCHW activation and
    the operand blocks are gathered in-kernel. `tiles` and `geom` are
    mandatory statics — the tile grid defines the block geometry, the
    conv geometry defines the address plan."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .mapping import pad_activation_flat

    if tiles is None or geom is None:
        raise ValueError(
            "implicit im2col needs static tiles=(bk, bn, adc_bits) and "
            "a conv_geom tuple")
    n = w.shape[1]
    rb, co, m, kdim, bm, bn, bk, adc_levels = _implicit_plan_arrays(
        x.shape, geom, tiles)
    xflat = pad_activation_flat(x, geom)[None, :]

    def pad(a, r, c):
        return jnp.pad(a, ((0, -a.shape[0] % r), (0, -a.shape[1] % c)))

    wp = pad(w, bk, bn)
    bp = pad(broken, bk, bn)
    sp = pad(stuck, bk, bn)
    gk = wp.shape[0] // bk
    gn = wp.shape[1] // bn
    on_tpu = jax.default_backend() == "tpu"
    levels = _q_levels(q_bits)
    # identical quantization grid to the premat launch: max-abs over
    # the padded weight matrix (padding zeros never raise it)
    scale = ([jnp.max(jnp.abs(wp)).reshape(1)] if levels else [])
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale_spec = [smem] if levels else []
    xspec = pl.BlockSpec((1, xflat.shape[1]), lambda i, j, k: (0, 0))
    rbspec = pl.BlockSpec((1, bm), lambda i, j, k: (0, 0))
    cospec = pl.BlockSpec((1, bk), lambda i, j, k: (0, k))
    wspec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    common = dict(
        grid=(1, gn, gk),
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((bm, wp.shape[1]), jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _make_implicit_kernel(levels, adc_levels, m, kdim, bk),
            in_specs=[smem] + scale_spec
            + [xspec, rbspec, cospec, wspec, wspec, wspec, smem],
            **common,
        )(jnp.asarray([seed], jnp.int32), *scale, xflat, rb, co, wp,
          bp, sp, sig)
    else:
        # same host draw as the premat interpret branch: PRNGKey(seed)
        # over the padded (Kp, Np) weight shape -> identical noise
        eps = jax.random.normal(jax.random.PRNGKey(seed), wp.shape,
                                jnp.float32)
        out = pl.pallas_call(
            _make_implicit_kernel_hostnoise(levels, adc_levels, m,
                                            kdim, bk),
            in_specs=scale_spec
            + [xspec, rbspec, cospec, wspec, wspec, wspec, wspec,
               smem],
            interpret=True,
            **common,
        )(*scale, xflat, rb, co, wp, bp, sp, eps, sig)
    return out[:m, :n]


def _pallas_forward_implicit_batched(x, w, broken, stuck, seeds, sigma,
                                     q_bits=0, tiles=None, geom=None):
    """Config-batched implicit launch: x is the raw NCHW activation,
    SHARED (4-D) or per-lane (5-D, leading config axis); w/broken/stuck
    (C, K, N) and seeds (C,) per lane. One pallas_call over grid
    (C, 1, gn, gk) — neither the per-lane weights nor ANY patch matrix
    ever materialize in HBM."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .mapping import pad_activation_flat

    if tiles is None or geom is None:
        raise ValueError(
            "implicit im2col needs static tiles=(bk, bn, adc_bits) and "
            "a conv_geom tuple")
    cfg = w.shape[0]
    x_batched = x.ndim == 5
    n = w.shape[2]
    rb, co, m, kdim, bm, bn, bk, adc_levels = _implicit_plan_arrays(
        x.shape[-4:], geom, tiles)
    xflat = pad_activation_flat(x, geom)
    if not x_batched:
        xflat = xflat[None, :]

    def pad3(a, r, c):
        return jnp.pad(a, ((0, 0), (0, -a.shape[1] % r),
                           (0, -a.shape[2] % c)))

    wp = pad3(w, bk, bn)
    bp = pad3(broken, bk, bn)
    sp = pad3(stuck, bk, bn)
    gk = wp.shape[1] // bk
    gn = wp.shape[2] // bn
    on_tpu = jax.default_backend() == "tpu"
    levels = _q_levels(q_bits)
    draw = bool(sigma)
    scale = ([jnp.max(jnp.abs(wp), axis=(1, 2))] if levels else [])
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale_spec = [smem] if levels else []
    fdim = xflat.shape[1]
    xspec = (pl.BlockSpec((1, fdim), lambda c, i, j, k: (c, 0))
             if x_batched
             else pl.BlockSpec((1, fdim), lambda c, i, j, k: (0, 0)))
    rbspec = pl.BlockSpec((1, bm), lambda c, i, j, k: (0, 0))
    cospec = pl.BlockSpec((1, bk), lambda c, i, j, k: (0, k))
    wspec = pl.BlockSpec((1, bk, bn), lambda c, i, j, k: (c, k, j))
    common = dict(
        grid=(cfg, 1, gn, gk),
        out_specs=pl.BlockSpec((1, bm, bn), lambda c, i, j, k: (c, i, j)),
        out_shape=jax.ShapeDtypeStruct((cfg, bm, wp.shape[2]),
                                       jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _make_implicit_batched_kernel(levels, draw, adc_levels, m,
                                          kdim, bk),
            in_specs=[smem] + scale_spec
            + [xspec, rbspec, cospec, wspec, wspec, wspec, smem],
            **common,
        )(jnp.asarray(seeds, jnp.int32), *scale, xflat, rb, co, wp,
          bp, sp, sig)
    else:
        eps = ([jax.vmap(lambda s: jax.random.normal(
                    jax.random.PRNGKey(s), wp.shape[1:], jnp.float32))(
                        seeds)] if draw else [])
        eps_spec = [wspec] if draw else []
        out = pl.pallas_call(
            _make_implicit_batched_kernel_hostnoise(levels, draw,
                                                    adc_levels, m,
                                                    kdim, bk),
            in_specs=scale_spec
            + [xspec, rbspec, cospec, wspec, wspec, wspec]
            + eps_spec + [smem],
            interpret=True,
            **common,
        )(*scale, xflat, rb, co, wp, bp, sp, *eps, sig)
    return out[:, :m, :n]


@functools.lru_cache(maxsize=None)
def _vmappable_implicit(sigma: float, q_bits: int, tiles, geom,
                        shard_mesh=None):
    """`_vmappable_forward`'s implicit-im2col twin: the SAME custom_vmap
    dispatch rules (full (w, broken, stuck, seed) batch -> one
    config-grid launch; mixed batching -> per-lane single kernels under
    lax.map; `shard_mesh` wraps the dispatch in shard_map over the
    config axis), keyed additionally by the static conv geometry that
    drives the address plan."""
    import jax.custom_batching

    @jax.custom_batching.custom_vmap
    def fwd(x, w, broken, stuck, seed):
        return _pallas_forward_implicit(x, w, broken, stuck, seed,
                                        sigma, q_bits, tiles, geom)

    @fwd.def_vmap
    def _rule(axis_size, in_batched, x, w, broken, stuck, seed):
        wb, bb, sb, seedb = in_batched[1:]   # x may be shared

        def dispatch(x, w, broken, stuck, seed):
            if wb and bb and sb and seedb:
                return _pallas_forward_implicit_batched(
                    x, w, broken, stuck, seed, sigma, q_bits, tiles,
                    geom)
            return per_lane_map(
                lambda *lane: _pallas_forward_implicit(
                    *lane, sigma, q_bits, tiles, geom),
                (x, w, broken, stuck, seed), in_batched)

        if shard_mesh is not None:
            from jax.sharding import PartitionSpec as P
            out = config_shard_map(
                dispatch, shard_mesh, (x, w, broken, stuck, seed),
                in_batched, out_specs=P("config", None, None))
        else:
            out = dispatch(x, w, broken, stuck, seed)
        return out, True
    return fwd


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def crossbar_conv_matmul(x, w, broken, stuck, seed, sigma, q_bits=0,
                         tiles=None, geom=None, shard_mesh=None):
    """`crossbar_matmul` for a tiled Convolution WITHOUT materializing
    its im2col patch operand: x is the raw (N, C, H, W) activation,
    `geom` the static `conv_geom` tuple (kh, kw, sh, sw, ph, pw, dh,
    dw), and each (bm, bk) operand block is gathered inside the kernel
    from the flat zero-padded activation via the precomputed additive
    address plan (fault/mapping.py). w/broken/stuck are the layer's
    (K, N) im2col crossbar view, exactly as the premat call site passes
    them; `tiles` is mandatory (the tile grid defines the kernel block
    geometry). Returns the (M, N) = (N*OH*OW, C_out) GEMM result —
    bit-identical to `crossbar_matmul(patch_rows(x), ...)` because the
    in-kernel gather reads the same exact values Precision.HIGHEST
    patch extraction copies, and every weight-side op is shared code.

    vmap / `shard_mesh` semantics are `crossbar_matmul`'s, via the same
    custom_vmap + shard_map seams. Backward (v1, recorded by the engine
    resolution): cotangents replay the premat patches-based VJP — the
    patch matrix IS materialized in the backward, dx flowing through
    the exact patch-extraction transpose and dw through
    patch_rows(x).T @ g with broken cells zeroed, so training cotangent
    bytes match the premat path too."""
    if tiles is None or geom is None:
        raise ValueError(
            "crossbar_conv_matmul needs static tiles=(bk, bn, adc_bits) "
            "and a conv_geom tuple")
    return _vmappable_implicit(float(sigma), int(q_bits), tiles, geom,
                               shard_mesh)(
        x, w, broken.astype(jnp.float32), stuck.astype(jnp.float32),
        seed)


def _ccm_fwd(x, w, broken, stuck, seed, sigma, q_bits, tiles, geom,
             shard_mesh):
    y = crossbar_conv_matmul(x, w, broken, stuck, seed, sigma, q_bits,
                             tiles, geom, shard_mesh)
    return y, (x, w, broken, stuck)


def _ccm_bwd(sigma, q_bits, tiles, geom, shard_mesh, res, g):
    # the premat backward, replayed exactly (same products, same
    # order): dx via the patch-extraction transpose, dw against the
    # forward's patch rows with broken cells zeroed. The patch matrix
    # materializes HERE only — the v1 trade the resolution records.
    from .mapping import conv_patch_rows
    x, w, broken, stuck = res
    wv = w
    if q_bits:
        wv = _quantize_tile(w, jnp.max(jnp.abs(w)), _q_levels(q_bits))
    w_masked = jnp.where(broken, stuck.astype(w.dtype), wv)
    xm, patch_vjp = jax.vjp(lambda t: conv_patch_rows(t, geom), x)
    dxm = g @ w_masked.T
    dx, = patch_vjp(dxm)
    dw = xm.T @ g
    dw = jnp.where(broken, 0.0, dw)
    return dx, dw, None, None, None


crossbar_conv_matmul.defvjp(_ccm_fwd, _ccm_bwd)


def tiled_crossbar_matmul(x, w_eff, bk: int, bn: int, adc_bits: int,
                          preferred_element_type=None):
    """The tiled crossbar read over an ALREADY-effective weight matrix
    (fault/mapping.py):

        y[:, jt] = sum_kt quantize_ste(x[:, kt] @ w_eff[kt, jt])

    — each (kt, jt) cell block is one physical crossbar tile whose
    analog MAC output passes through its own `adc_bits`-wide ADC
    (dynamic per-tile range, quantize_ste's per-call default) before
    the digital accumulation across the K-tile axis. This is the pure
    twin of the kernel's `_apply_tile` + `_adc_read` sequence (the
    check_tiled_mapping.py parity axis) AND the jax-engine layer path
    (ops/common.py — there `w_eff` is the perturbed weight the solver
    installed). Straight-through gradients throughout (`quantize_ste`
    carries the STE identity).

    Blocks are zero-padded to the kernel's exact launch shapes
    (8-aligned M block, full (bk, bn) tiles) before the dot: padding
    changes no value (zero rows/cols contribute zero, an abs-max is
    never raised by zeros) but it makes every dot the SAME shaped op
    the kernel runs, so the two engines round identically and the
    per-lane comparison in scripts/check_tiled_mapping.py can be
    bit-exact instead of tolerance-based."""
    bk, bn = int(bk), int(bn)
    K, N = w_eff.shape
    m = x.shape[0]
    bm = _m_block(m)
    xp = jnp.pad(x, ((0, bm - m), (0, -K % bk)))
    wp = jnp.pad(w_eff, ((0, -K % bk), (0, -N % bn)))
    Kp, Np = wp.shape
    cols = []
    for n0 in range(0, Np, bn):
        acc = None
        for k0 in range(0, Kp, bk):
            part = jnp.dot(xp[:, k0:k0 + bk], wp[k0:k0 + bk,
                                                 n0:n0 + bn],
                           preferred_element_type=preferred_element_type)
            part = quantize_ste(part, int(adc_bits))
            acc = part if acc is None else acc + part
        cols.append(acc)
    y = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=1)
    return y[:m, :N]


def tiled_crossbar_matmul_slabs(x_slab, w_eff, bk: int, bn: int,
                                adc_bits: int, m: int,
                                preferred_element_type=None):
    """`tiled_crossbar_matmul` with a LAZY x operand: `x_slab(k0, k1)`
    returns the (m, k1-k0) column slab of the conceptual (m, K) matrix
    for K-rows [k0, k1), k1 clipped to K. The conv im2col path's
    "tilewise" operand mode (ops/vision.py, RRAM_CONV_IM2COL): instead
    of materializing the full patch matrix, each K-tile's patch slab is
    extracted on demand inside the tile loop — lower peak memory, the
    extraction repeated per K-tile instead of once.

    Bit-identity contract with the premat twin: each slab is zero-padded
    to the identical (bm, bk) block the premat path slices out of its
    padded operand, the dots run K-tile-outer but accumulate into each
    N-tile's accumulator in the same increasing-k0 order, and the
    per-tile ADC sees the identical block bytes — so a slab function
    whose values match the premat operand's columns yields bit-identical
    output (guarded by tests/test_conv_tiles.py)."""
    bk, bn = int(bk), int(bn)
    K, N = w_eff.shape
    m = int(m)
    bm = _m_block(m)
    wp = jnp.pad(w_eff, ((0, -K % bk), (0, -N % bn)))
    Kp, Np = wp.shape
    accs = [None] * (Np // bn)
    for k0 in range(0, Kp, bk):
        k1 = min(k0 + bk, K)
        slab = jnp.pad(x_slab(k0, k1),
                       ((0, bm - m), (0, bk - (k1 - k0))))
        for j, n0 in enumerate(range(0, Np, bn)):
            part = jnp.dot(slab, wp[k0:k0 + bk, n0:n0 + bn],
                           preferred_element_type=preferred_element_type)
            part = quantize_ste(part, int(adc_bits))
            accs[j] = part if accs[j] is None else accs[j] + part
    y = accs[0] if len(accs) == 1 else jnp.concatenate(accs, axis=1)
    return y[:m, :N]


def reference_crossbar_matmul(x, w, broken, stuck, key, sigma: float,
                              q_bits: int = 0, tiles=None):
    """Pure-JAX semantic reference for crossbar_matmul (exact match at
    sigma == 0; same distribution otherwise, different noise stream).
    `q_bits` mirrors the kernel's in-VMEM quantization through
    `quantize_ste` — same grid, same straight-through forward values.
    `tiles` = the kernel's (bk, bn, adc_bits) tiled-mapping parameter:
    the matmul becomes per-tile ADC-quantized partial sums accumulated
    across the K-tile axis (`tiled_crossbar_matmul`)."""
    wq = quantize_ste(w, q_bits) if q_bits else w
    w_eff = perturb_weight(wq, broken, stuck, key, sigma)
    if tiles is not None:
        return tiled_crossbar_matmul(x, w_eff, tiles[0], tiles[1],
                                     tiles[2])
    return x @ w_eff

"""Hardware-aware forward: crossbar conductance noise + stuck-cell clamp +
ADC quantization injected into the forward pass with straight-through
gradients.

This is the TPU framework's extension beyond the reference (SURVEY §7 build
plan item 3: "differentiable Pallas noise-injection kernel — conductance
variation sigma, ADC/DAC quantization, stuck masks fused into the GEMM —
with custom_vjp straight-through for hardware-aware training"). The
reference only injects faults into STORED weights after the update
(failure_maker.cu:23-40); here every forward READ can additionally see the
analog crossbar's conductance variation, so training converges to
noise-robust weights.

Two implementations with one contract:

- `perturb_weight` / `quantize_ste`: pure JAX, jit/vmap-safe everywhere
  (the Monte-Carlo sweep vmaps them per config). Straight-through is the
  `x + stop_gradient(f(x) - x)` identity, so d(w_eff)/dw == 1 while the
  forward sees the perturbed value.
- `crossbar_matmul`: a fused Pallas TPU kernel computing
  y = x @ where(broken, stuck, w * (1 + sigma*eps)) with the noise drawn
  IN-KERNEL (pltpu PRNG + Box-Muller) per weight tile — the noisy weight
  matrix never materializes in HBM. custom_vjp backward uses the CLEAN
  masked weights (noise treated as a forward-only perturbation, the
  standard QAT straight-through choice); with sigma == 0 forward and
  backward match the pure path exactly.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def perturb_weight(w, broken, stuck, key, sigma: float):
    """Forward-read value of a crossbar weight array: multiplicative
    Gaussian conductance variation on live cells, stuck value on broken
    ones. Straight-through: gradients pass to `w` unchanged."""
    noisy = w * (1.0 + sigma * jax.random.normal(key, w.shape, w.dtype)) \
        if sigma else w
    w_eff = jnp.where(broken, stuck.astype(w.dtype), noisy)
    return w + jax.lax.stop_gradient(w_eff - w)


def quantize_ste(x, bits: int, max_abs=None):
    """Symmetric uniform quantization (ADC model) with straight-through
    gradients. `max_abs` defaults to the per-call dynamic range."""
    if not bits:
        return x
    if bits < 2:
        # bits == 1 would give zero symmetric levels -> scale = inf -> NaN
        raise ValueError(f"quantize_ste needs bits >= 2, got {bits}")
    if max_abs is None:
        max_abs = jnp.max(jnp.abs(x))
    levels = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(max_abs, 1e-12) / levels
    q = jnp.clip(jnp.round(x / scale), -levels, levels) * scale
    return x + jax.lax.stop_gradient(q - x)


# ---------------------------------------------------------------------------
# Pallas fused kernel

def _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref, sigma, eps):
    noisy = w_ref[:] * (1.0 + sigma * eps)
    w_eff = jnp.where(broken_ref[:] > 0, stuck_ref[:], noisy)
    o_ref[:] += jnp.dot(x_ref[:], w_eff,
                        preferred_element_type=jnp.float32)


def _crossbar_kernel(seed_ref, x_ref, w_ref, broken_ref, stuck_ref,
                     sigma_ref, o_ref):
    """One (bm, bn) output tile, accumulating over the K grid axis; the
    weight tile is perturbed in VMEM before hitting the MXU. The PRNG is
    seeded per (j, k) tile so every x-tile sees the SAME weight noise."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    w = w_ref[:]
    # Seed and tile index are SEPARATE seed words: with a single word
    # `seed + j*nk + k`, seed s+1 tile t would replay seed s tile t+1 —
    # sequential Monte-Carlo seeds would share almost all their noise.
    pltpu.prng_seed(seed_ref[0], j * nk + k)

    def uniform01(shape):
        # map raw 32-bit draws to [0,1) regardless of signed/unsigned
        # interpretation: scale then take the fractional part
        b = pltpu.prng_random_bits(shape)
        u = b.astype(jnp.float32) * (1.0 / 4294967296.0)
        return u - jnp.floor(u)

    # Box-Muller -> N(0,1) per weight element
    u1 = jnp.maximum(uniform01(w.shape), 1e-12)
    u2 = uniform01(w.shape)
    eps = jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * np.pi * u2)
    _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref,
                sigma_ref[0], eps)


def _crossbar_kernel_hostnoise(x_ref, w_ref, broken_ref, stuck_ref,
                               eps_ref, sigma_ref, o_ref):
    """Interpret-mode twin for off-TPU hosts: identical math, but the
    Gaussian draw arrives as an input (pltpu's in-kernel PRNG has no CPU
    interpret lowering)."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref,
                sigma_ref[0], eps_ref[:])


def _pallas_forward(x, w, broken, stuck, seed, sigma,
                    bm=128, bn=128, bk=128):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, kdim = x.shape
    _, n = w.shape

    def pad(a, r, c):
        return jnp.pad(a, ((0, -a.shape[0] % r), (0, -a.shape[1] % c)))

    xp = pad(x, bm, bk)
    wp = pad(w, bk, bn)
    bp = pad(broken, bk, bn)
    sp = pad(stuck, bk, bn)
    gm, gk = xp.shape[0] // bm, xp.shape[1] // bk
    gn = wp.shape[1] // bn
    on_tpu = jax.default_backend() == "tpu"
    wspec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    common = dict(
        grid=(gm, gn, gk),
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]),
                                       jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _crossbar_kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),  # seed
                      pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      wspec, wspec, wspec,
                      pl.BlockSpec(memory_space=pltpu.SMEM)],  # sigma
            **common,
        )(jnp.asarray([seed], jnp.int32), xp, wp, bp, sp, sig)
    else:
        eps = jax.random.normal(jax.random.PRNGKey(seed), wp.shape,
                                jnp.float32)
        out = pl.pallas_call(
            _crossbar_kernel_hostnoise,
            in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      wspec, wspec, wspec, wspec,
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            interpret=True,
            **common,
        )(xp, wp, bp, sp, eps, sig)
    return out[:m, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def crossbar_matmul(x, w, broken, stuck, seed, sigma):
    """y = x @ where(broken, stuck, w * (1 + sigma*eps)) as one fused
    Pallas kernel (noise generated in VMEM, never materialized in HBM).

    x: (M, K) f32; w: (K, N) f32; broken: (K, N) bool; stuck: (K, N) f32;
    seed: python int (static under jit); sigma: python float (static).
    Backward is straight-through against the CLEAN masked weights."""
    return _pallas_forward(x, w, broken.astype(jnp.float32),
                           stuck.astype(jnp.float32), seed, sigma)


def _cm_fwd(x, w, broken, stuck, seed, sigma):
    y = crossbar_matmul(x, w, broken, stuck, seed, sigma)
    return y, (x, w, broken, stuck)


def _cm_bwd(sigma, res, g):
    x, w, broken, stuck = res
    w_masked = jnp.where(broken, stuck.astype(w.dtype), w)
    dx = g @ w_masked.T
    dw = x.T @ g
    # stuck cells take no gradient (their stored value is clamped by the
    # fault engine anyway; matches d/dw of where(broken, stuck, w))
    dw = jnp.where(broken, 0.0, dw)
    return dx, dw, None, None, None


crossbar_matmul.defvjp(_cm_fwd, _cm_bwd)


def reference_crossbar_matmul(x, w, broken, stuck, key, sigma: float):
    """Pure-JAX semantic reference for crossbar_matmul (exact match at
    sigma == 0; same distribution otherwise, different noise stream)."""
    return x @ perturb_weight(w, broken, stuck, key, sigma)

"""Hardware-aware forward: crossbar conductance noise + stuck-cell clamp +
ADC quantization injected into the forward pass with straight-through
gradients.

This is the TPU framework's extension beyond the reference (SURVEY §7 build
plan item 3: "differentiable Pallas noise-injection kernel — conductance
variation sigma, ADC/DAC quantization, stuck masks fused into the GEMM —
with custom_vjp straight-through for hardware-aware training"). The
reference only injects faults into STORED weights after the update
(failure_maker.cu:23-40); here every forward READ can additionally see the
analog crossbar's conductance variation, so training converges to
noise-robust weights.

Two implementations with one contract:

- `perturb_weight` / `quantize_ste`: pure JAX, jit/vmap-safe everywhere
  (the Monte-Carlo sweep vmaps them per config). Straight-through is the
  `x + stop_gradient(f(x) - x)` identity, so d(w_eff)/dw == 1 while the
  forward sees the perturbed value.
- `crossbar_matmul`: a fused Pallas TPU kernel computing
  y = x @ where(broken, stuck, quantize(w) * (1 + sigma*eps)) with the
  noise drawn IN-KERNEL (pltpu PRNG + Box-Muller) per weight tile and
  the optional `q_bits` weight quantization (the ADC/DAC-grid operating
  point, same symmetric-uniform formula as `quantize_ste`) applied to
  the VMEM tile — neither the noisy nor the quantized weight matrix
  ever materializes in HBM. custom_vjp backward uses the CLEAN masked
  weights (noise and quantization treated as forward-only
  perturbations, the standard QAT straight-through choice); with
  sigma == 0 and q_bits == 0 forward and backward match the pure path
  exactly.

ENGINE MATRIX — the single source for the `hw_engine` selection
(referenced by core/registry.py `LayerContext.crossbar` and
`Solver.make_train_step`; mirrors the reference's Caffe-vs-cuDNN engine
choice, layer_factory.cpp:38):

  ==========  ================================  ==============================
  hw_engine   single config (Solver)            Monte-Carlo sweep (SweepRunner)
  ==========  ================================  ==============================
  "jax"       perturb_weight + quantize_ste     same, vmapped per config —
              (pure JAX; vmap/GSPMD-safe        the semantic REFERENCE path
              everywhere)                       and the sweep default
  "pallas"    fused crossbar_matmul kernel      config-batched kernel: the
              (noise + quantize drawn/applied   vmap over (w, broken, stuck,
              in VMEM)                          seed) dispatches to ONE
                                                (config, m, n, k)-grid launch
                                                covering every lane
  "auto"      pallas on the TPU backend,        jax (sweeps opt in to pallas
              jax elsewhere                     explicitly via
                                                SweepRunner(engine=...))
  ==========  ================================  ==============================

Fallbacks (every one loud or semantics-preserving, never silent wrong
answers): under a `compute_dtype` below f32 the kernel still computes
in f32 — the call site (ops/common.py) casts x/w up around the fused
call and the output/cotangents back down, so activations keep the
half-width HBM traffic while the crossbar read keeps f32 numerics
("auto" stays conservative and engages pallas only at native f32; an
explicit hw_engine="pallas" composes with any compute_dtype); the
dp/tp/pp wrappers force "jax" (the kernel has no GSPMD partitioning
rule); and a
vmap batching pattern that does not batch ALL of w/broken/stuck/seed
(x may be shared or per-config) runs the single-config kernel per lane
under `lax.map` (identical numerics, no fusion win).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def perturb_weight(w, broken, stuck, key, sigma: float):
    """Forward-read value of a crossbar weight array: multiplicative
    Gaussian conductance variation on live cells, stuck value on broken
    ones. Straight-through: gradients pass to `w` unchanged."""
    noisy = w * (1.0 + sigma * jax.random.normal(key, w.shape, w.dtype)) \
        if sigma else w
    w_eff = jnp.where(broken, stuck.astype(w.dtype), noisy)
    return w + jax.lax.stop_gradient(w_eff - w)


def quantize_ste(x, bits: int, max_abs=None):
    """Symmetric uniform quantization (ADC model) with straight-through
    gradients. `max_abs` defaults to the per-call dynamic range."""
    if not bits:
        return x
    if bits < 2:
        # bits == 1 would give zero symmetric levels -> scale = inf -> NaN
        raise ValueError(f"quantize_ste needs bits >= 2, got {bits}")
    if max_abs is None:
        max_abs = jnp.max(jnp.abs(x))
    levels = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(max_abs, 1e-12) / levels
    q = jnp.clip(jnp.round(x / scale), -levels, levels) * scale
    return x + jax.lax.stop_gradient(q - x)


# ---------------------------------------------------------------------------
# Pallas fused kernel

def _q_levels(q_bits: int) -> float:
    """Symmetric quantization level count for a bit width (0 = off);
    the same 2^(bits-1)-1 grid `quantize_ste` uses."""
    if not q_bits:
        return 0.0
    if q_bits < 2:
        raise ValueError(f"crossbar q_bits needs bits >= 2, got {q_bits}")
    return float(2 ** (q_bits - 1) - 1)


def _quantize_tile(w, scale, levels: float):
    """quantize_ste's forward formula on a VMEM tile: `scale` is the
    whole (per-config) weight matrix's max-abs, computed outside the
    kernel (the grid must be uniform across tiles, like the pure path's
    per-call dynamic range)."""
    s = jnp.maximum(scale, 1e-12) / levels
    return jnp.clip(jnp.round(w / s), -levels, levels) * s


def _gauss_tile(shape):
    """In-kernel N(0,1) tile draw (call after `pltpu.prng_seed`): raw
    32-bit PRNG words -> [0,1) by scale + fractional part (proof
    against signed/unsigned interpretation) -> Box-Muller. The ONE
    definition shared by the single-config and config-batched kernels —
    the batched-vs-per-lane bit-exactness contract hangs on these ops
    matching exactly."""
    from jax.experimental.pallas import tpu as pltpu

    def uniform01(s):
        b = pltpu.prng_random_bits(s)
        u = b.astype(jnp.float32) * (1.0 / 4294967296.0)
        return u - jnp.floor(u)

    u1 = jnp.maximum(uniform01(shape), 1e-12)
    u2 = uniform01(shape)
    return jnp.sqrt(-2.0 * jnp.log(u1)) * jnp.cos(2.0 * np.pi * u2)


def _w_eff(w, broken, stuck, sigma, eps, q_levels, scale):
    """The effective crossbar read of one weight tile — the semantic
    sequence every kernel variant shares: optional ADC-grid
    quantization, forward-only conductance noise (`eps=None` skips the
    multiply: the sigma == 0 sweep builds no PRNG at all), stuck
    clamp."""
    if q_levels:
        w = _quantize_tile(w, scale, q_levels)
    if eps is not None:
        w = w * (1.0 + sigma * eps)
    return jnp.where(broken > 0, stuck, w)


def _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref, sigma, eps,
                q_levels=0.0, scale=None):
    w_eff = _w_eff(w_ref[:], broken_ref[:], stuck_ref[:], sigma, eps,
                   q_levels, scale)
    o_ref[:] += jnp.dot(x_ref[:], w_eff,
                        preferred_element_type=jnp.float32)


def _make_crossbar_kernel(q_levels: float):
    """One (bm, bn) output tile, accumulating over the K grid axis; the
    weight tile is quantized + perturbed in VMEM before hitting the MXU.
    The PRNG is seeded per (j, k) tile so every x-tile sees the SAME
    weight noise. `q_levels` is static: 0 builds the exact historical
    kernel signature (no scale input)."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (seed_ref, scale_ref, x_ref, w_ref, broken_ref, stuck_ref,
             sigma_ref, o_ref) = refs
        else:
            (seed_ref, x_ref, w_ref, broken_ref, stuck_ref, sigma_ref,
             o_ref) = refs
            scale_ref = None
        j = pl.program_id(1)
        k = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        # Seed and tile index are SEPARATE seed words: with a single word
        # `seed + j*nk + k`, seed s+1 tile t would replay seed s tile t+1
        # — sequential Monte-Carlo seeds would share almost all their
        # noise.
        pltpu.prng_seed(seed_ref[0], j * nk + k)
        eps = _gauss_tile(w_ref[:].shape)
        _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref,
                    sigma_ref[0], eps, q_levels,
                    scale_ref[0] if q_levels else None)
    return kernel


def _make_crossbar_kernel_hostnoise(q_levels: float):
    """Interpret-mode twin for off-TPU hosts: identical math, but the
    Gaussian draw arrives as an input (pltpu's in-kernel PRNG has no CPU
    interpret lowering)."""
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (scale_ref, x_ref, w_ref, broken_ref, stuck_ref, eps_ref,
             sigma_ref, o_ref) = refs
        else:
            (x_ref, w_ref, broken_ref, stuck_ref, eps_ref, sigma_ref,
             o_ref) = refs
            scale_ref = None

        @pl.when(pl.program_id(2) == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        _apply_tile(x_ref, w_ref, broken_ref, stuck_ref, o_ref,
                    sigma_ref[0], eps_ref[:], q_levels,
                    scale_ref[0] if q_levels else None)
    return kernel


def _pallas_forward(x, w, broken, stuck, seed, sigma, q_bits=0,
                    bm=128, bn=128, bk=128):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, kdim = x.shape
    _, n = w.shape

    def pad(a, r, c):
        return jnp.pad(a, ((0, -a.shape[0] % r), (0, -a.shape[1] % c)))

    xp = pad(x, bm, bk)
    wp = pad(w, bk, bn)
    bp = pad(broken, bk, bn)
    sp = pad(stuck, bk, bn)
    gm, gk = xp.shape[0] // bm, xp.shape[1] // bk
    gn = wp.shape[1] // bn
    on_tpu = jax.default_backend() == "tpu"
    levels = _q_levels(q_bits)
    # the quantization grid spans the WHOLE weight matrix (quantize_ste's
    # per-call dynamic range), so the max-abs reduction runs outside the
    # tile loop; padding is zeros, so it can ride the padded array
    scale = ([jnp.max(jnp.abs(wp)).reshape(1)] if levels else [])
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale_spec = [smem] if levels else []
    wspec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    common = dict(
        grid=(gm, gn, gk),
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]),
                                       jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _make_crossbar_kernel(levels),
            in_specs=[smem] + scale_spec + [            # seed (+ scale)
                      pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      wspec, wspec, wspec,
                      smem],                            # sigma
            **common,
        )(jnp.asarray([seed], jnp.int32), *scale, xp, wp, bp, sp, sig)
    else:
        eps = jax.random.normal(jax.random.PRNGKey(seed), wp.shape,
                                jnp.float32)
        out = pl.pallas_call(
            _make_crossbar_kernel_hostnoise(levels),
            in_specs=scale_spec + [
                      pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      wspec, wspec, wspec, wspec,
                      smem],
            interpret=True,
            **common,
        )(*scale, xp, wp, bp, sp, eps, sig)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# config-batched sweep kernel: one (config, m, n, k) grid launch forms
# every lane's faulty+noisy+quantized weights in VMEM — the per-lane
# weight matrices never round-trip HBM (ROADMAP item 3 / ISSUE 7 (a))

def _make_batched_kernel(q_levels: float, draw_noise: bool,
                         x_batched: bool):
    """The config-grid twin of `_make_crossbar_kernel`: grid axis 0 is
    the config lane; each lane is seeded with ITS OWN seed word and the
    SAME (j*nk + k) tile index, so per-lane noise streams are
    bit-identical to per-lane single-config kernel launches — the
    batched-vs-per-lane parity tests compare exactly, not
    statistically. `draw_noise` is static: a sigma == 0 sweep (e.g. the
    pure ternary operating point) skips the Box-Muller draw entirely.
    `x_batched` is static: False streams ONE shared (M, K) input to
    every lane (the genetic-search eval pattern); True gives each lane
    its own input slab (the training sweep pattern — activations differ
    per config because the upstream weights do)."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    def kernel(*refs):
        if q_levels:
            (seed_ref, scale_ref, x_ref, w_ref, broken_ref, stuck_ref,
             sigma_ref, o_ref) = refs
        else:
            (seed_ref, x_ref, w_ref, broken_ref, stuck_ref, sigma_ref,
             o_ref) = refs
            scale_ref = None
        c = pl.program_id(0)
        j = pl.program_id(2)
        k = pl.program_id(3)
        nk = pl.num_programs(3)

        @pl.when(k == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        w = w_ref[0]
        if draw_noise:
            # per-lane seed word + the SAME (j*nk + k) tile index as
            # the single-config kernel -> bit-identical per-lane noise
            pltpu.prng_seed(seed_ref[c], j * nk + k)
            eps = _gauss_tile(w.shape)
        else:
            eps = None
        w_eff = _w_eff(w, broken_ref[0], stuck_ref[0],
                       sigma_ref[0] if draw_noise else None, eps,
                       q_levels, scale_ref[c] if q_levels else None)
        xt = x_ref[0] if x_batched else x_ref[:]
        o_ref[0] += jnp.dot(xt, w_eff,
                            preferred_element_type=jnp.float32)
    return kernel


def _make_batched_kernel_hostnoise(q_levels: float, draw_noise: bool,
                                   x_batched: bool):
    """Interpret-mode twin of `_make_batched_kernel` (per-lane Gaussian
    draws arrive as a (config, K, N) input)."""
    import jax.experimental.pallas as pl

    def kernel(*refs):
        refs = list(refs)
        scale_ref = refs.pop(0) if q_levels else None
        x_ref, w_ref, broken_ref, stuck_ref = refs[:4]
        refs = refs[4:]
        eps_ref = refs.pop(0) if draw_noise else None
        sigma_ref, o_ref = refs
        c = pl.program_id(0)

        @pl.when(pl.program_id(3) == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        w_eff = _w_eff(w_ref[0], broken_ref[0], stuck_ref[0],
                       sigma_ref[0] if draw_noise else None,
                       eps_ref[0] if draw_noise else None,
                       q_levels, scale_ref[c] if q_levels else None)
        xt = x_ref[0] if x_batched else x_ref[:]
        o_ref[0] += jnp.dot(xt, w_eff,
                            preferred_element_type=jnp.float32)
    return kernel


def _pallas_forward_batched(x, w, broken, stuck, seeds, sigma, q_bits=0,
                            bm=128, bn=128, bk=128):
    """The config-batched launch: x (M, K) SHARED across lanes or
    (C, M, K) per lane; w/broken/stuck (C, K, N) and seeds (C,) per
    lane; one pallas_call over grid (C, gm, gn, gk). Every lane's
    weight tile is formed in VMEM — per-lane weight matrices never
    materialize in HBM."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cfg = w.shape[0]
    x_batched = x.ndim == 3
    m, kdim = x.shape[-2:]
    n = w.shape[2]

    def pad2(a, r, c):
        return jnp.pad(a, ((0, -a.shape[0] % r), (0, -a.shape[1] % c)))

    def pad3(a, r, c):
        return jnp.pad(a, ((0, 0), (0, -a.shape[1] % r),
                           (0, -a.shape[2] % c)))

    xp = pad3(x, bm, bk) if x_batched else pad2(x, bm, bk)
    wp = pad3(w, bk, bn)
    bp = pad3(broken, bk, bn)
    sp = pad3(stuck, bk, bn)
    gm, gk = xp.shape[-2] // bm, xp.shape[-1] // bk
    gn = wp.shape[2] // bn
    on_tpu = jax.default_backend() == "tpu"
    levels = _q_levels(q_bits)
    draw = bool(sigma)
    # per-lane quantization grids (each config trains its own weights,
    # so each lane has its own dynamic range — matching what
    # quantize_ste computes per lane under the pure engine's vmap)
    scale = ([jnp.max(jnp.abs(wp), axis=(1, 2))] if levels else [])
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale_spec = [smem] if levels else []
    xspec = (pl.BlockSpec((1, bm, bk), lambda c, i, j, k: (c, i, k))
             if x_batched
             else pl.BlockSpec((bm, bk), lambda c, i, j, k: (i, k)))
    wspec = pl.BlockSpec((1, bk, bn), lambda c, i, j, k: (c, k, j))
    common = dict(
        grid=(cfg, gm, gn, gk),
        out_specs=pl.BlockSpec((1, bm, bn), lambda c, i, j, k: (c, i, j)),
        out_shape=jax.ShapeDtypeStruct((cfg, xp.shape[-2], wp.shape[2]),
                                       jnp.float32),
    )
    sig = jnp.asarray([sigma], jnp.float32)
    if on_tpu:
        out = pl.pallas_call(
            _make_batched_kernel(levels, draw, x_batched),
            in_specs=[smem] + scale_spec + [xspec, wspec, wspec, wspec,
                                            smem],
            **common,
        )(jnp.asarray(seeds, jnp.int32), *scale, xp, wp, bp, sp, sig)
    else:
        eps = ([jax.vmap(lambda s: jax.random.normal(
                    jax.random.PRNGKey(s), wp.shape[1:], jnp.float32))(
                        seeds)] if draw else [])
        eps_spec = [wspec] if draw else []
        out = pl.pallas_call(
            _make_batched_kernel_hostnoise(levels, draw, x_batched),
            in_specs=scale_spec + [xspec, wspec, wspec, wspec]
            + eps_spec + [smem],
            interpret=True,
            **common,
        )(*scale, xp, wp, bp, sp, *eps, sig)
    return out[:, :m, :n]


@functools.lru_cache(maxsize=None)
def _vmappable_forward(sigma: float, q_bits: int):
    """The engine-dispatch seam between the single-config and the
    config-batched kernel: an unbatched call lowers to the single
    kernel; a vmap over (w, broken, stuck, seed) — the Monte-Carlo
    sweep's config axis, with x either shared (genetic eval) or
    per-config (the training sweep: upstream per-config weights batch
    every activation) — dispatches to ONE config-grid launch; any other
    pattern falls back to per-lane single kernels under lax.map
    (identical numerics, no fusion)."""
    import jax.custom_batching

    @jax.custom_batching.custom_vmap
    def fwd(x, w, broken, stuck, seed):
        return _pallas_forward(x, w, broken, stuck, seed, sigma, q_bits)

    @fwd.def_vmap
    def _rule(axis_size, in_batched, x, w, broken, stuck, seed):
        xb, wb, bb, sb, seedb = in_batched
        if wb and bb and sb and seedb:
            out = _pallas_forward_batched(x, w, broken, stuck, seed,
                                          sigma, q_bits)
        else:
            # mixed batching (e.g. per-lane fault masks with shared
            # weights): run the single kernel per lane — unbatched
            # operands stay closure-captured, nothing is
            # broadcast-materialized
            def one(i):
                take = lambda v, b: v[i] if b else v
                return _pallas_forward(
                    take(x, xb), take(w, wb), take(broken, bb),
                    take(stuck, sb), take(seed, seedb), sigma, q_bits)
            out = jax.lax.map(one, jnp.arange(axis_size))
        return out, True
    return fwd


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def crossbar_matmul(x, w, broken, stuck, seed, sigma, q_bits=0):
    """y = x @ where(broken, stuck, quantize(w) * (1 + sigma*eps)) as
    one fused Pallas kernel (noise generated and the optional q_bits
    ADC-grid quantization applied in VMEM, never materialized in HBM).

    x: (M, K) f32; w: (K, N) f32; broken: (K, N) bool; stuck: (K, N) f32;
    seed: int scalar (python or traced); sigma: python float (static);
    q_bits: python int (static; 0 = no quantization, >= 2 = the
    symmetric-uniform grid `quantize_ste` models). Backward is
    straight-through against the CLEAN masked weights.

    vmap over (w, broken, stuck, seed) — the sweep's config axis, with
    x shared or per-config — dispatches to the config-batched kernel
    (one launch for every lane, per-lane noise streams bit-identical to
    per-lane single launches); see the ENGINE MATRIX in the module
    docstring."""
    return _vmappable_forward(float(sigma), int(q_bits))(
        x, w, broken.astype(jnp.float32), stuck.astype(jnp.float32),
        seed)


def _cm_fwd(x, w, broken, stuck, seed, sigma, q_bits):
    y = crossbar_matmul(x, w, broken, stuck, seed, sigma, q_bits)
    return y, (x, w, broken, stuck)


def _cm_bwd(sigma, q_bits, res, g):
    x, w, broken, stuck = res
    wv = w
    if q_bits:
        # dx flows through the values the forward actually used: the
        # ADC-grid weights (quantize_ste's STE differentiates x @ w_eff
        # with w_eff on the grid). dw stays straight-through to the
        # clean master weights.
        wv = _quantize_tile(w, jnp.max(jnp.abs(w)), _q_levels(q_bits))
    w_masked = jnp.where(broken, stuck.astype(w.dtype), wv)
    dx = g @ w_masked.T
    dw = x.T @ g
    # stuck cells take no gradient (their stored value is clamped by the
    # fault engine anyway; matches d/dw of where(broken, stuck, w))
    dw = jnp.where(broken, 0.0, dw)
    return dx, dw, None, None, None


crossbar_matmul.defvjp(_cm_fwd, _cm_bwd)


def reference_crossbar_matmul(x, w, broken, stuck, key, sigma: float,
                              q_bits: int = 0):
    """Pure-JAX semantic reference for crossbar_matmul (exact match at
    sigma == 0; same distribution otherwise, different noise stream).
    `q_bits` mirrors the kernel's in-VMEM quantization through
    `quantize_ste` — same grid, same straight-through forward values."""
    wq = quantize_ste(w, q_bits) if q_bits else w
    return x @ perturb_weight(wq, broken, stuck, key, sigma)

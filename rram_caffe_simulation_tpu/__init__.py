"""rram_caffe_simulation_tpu: a TPU-native (JAX/XLA/Pallas) re-design of the
RRAM-fault-simulating Caffe fork `fightingnoble/rram-caffe-simulation`.

Capability map (reference paths are relative to the reference repo):
- proto/    wire-compatible config & serialization schema (src/caffe/proto/caffe.proto)
- core/     fillers, parameter metadata, layer registry (filler.hpp, layer_factory.*)
- ops/      pure-JAX layer implementations (src/caffe/layers/*)
- net/      prototxt graph -> pure init/apply functions (src/caffe/net.cpp)
- solver/   Caffe-exact SGD-family solvers + train loop (src/caffe/solver*.cpp)
- fault/    RRAM cell-endurance fault engine + mitigation strategies
            (src/caffe/failure_maker.*, src/caffe/strategy.*)
- data/     host data pipeline (src/caffe/data_*, util/db*)
- parallel/ mesh-based data/config parallelism (src/caffe/parallel.*)
- utils/    io, snapshots, logging, timing (src/caffe/util/*)
- models/   prototxt model zoo (models/, examples/)
- tools/    CLI and experiment harness (tools/caffe.cpp, examples/cifar10/gaussian_failure)
"""

__version__ = "1.0.0"

from .proto import pb  # noqa: F401

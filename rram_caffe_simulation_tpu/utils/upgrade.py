"""Legacy prototxt / caffemodel format migration.

Pre-1.0 Caffe serialized nets in two older formats: V0 (a flat
``V0LayerParameter`` bag nested inside each ``layers`` entry) and V1
(``NetParameter.layers`` with an enum layer type). Published zoo weights are
mostly V1. This module migrates any of those, plus the smaller deprecations
(per-data-layer transform fields, net-level ``input`` fields, 3-param
BatchNorm, solver_type enum), to the current schema so that
``read_net_param``/``read_solver_param`` always hand the framework a modern
message.

Behavioral contract follows reference src/caffe/util/upgrade_proto.cpp
(upgrade_proto.hpp:14 UpgradeNetAsNeeded, :55 UpgradeV1Net, :80
UpgradeSolverAsNeeded); the implementation here is table-driven rather than
a field-by-field port.
"""
from __future__ import annotations

import logging

from ..proto import pb

log = logging.getLogger("caffe_tpu.upgrade")

V1 = pb.V1LayerParameter

# V1 enum -> current string type (reference upgrade_proto.cpp:877
# UpgradeV1LayerType).
V1_TYPE_NAMES = {
    V1.NONE: "",
    V1.ABSVAL: "AbsVal",
    V1.ACCURACY: "Accuracy",
    V1.ARGMAX: "ArgMax",
    V1.BNLL: "BNLL",
    V1.CONCAT: "Concat",
    V1.CONTRASTIVE_LOSS: "ContrastiveLoss",
    V1.CONVOLUTION: "Convolution",
    V1.DECONVOLUTION: "Deconvolution",
    V1.DATA: "Data",
    V1.DROPOUT: "Dropout",
    V1.DUMMY_DATA: "DummyData",
    V1.EUCLIDEAN_LOSS: "EuclideanLoss",
    V1.ELTWISE: "Eltwise",
    V1.EXP: "Exp",
    V1.FLATTEN: "Flatten",
    V1.HDF5_DATA: "HDF5Data",
    V1.HDF5_OUTPUT: "HDF5Output",
    V1.HINGE_LOSS: "HingeLoss",
    V1.IM2COL: "Im2col",
    V1.IMAGE_DATA: "ImageData",
    V1.INFOGAIN_LOSS: "InfogainLoss",
    V1.INNER_PRODUCT: "InnerProduct",
    V1.LRN: "LRN",
    V1.MEMORY_DATA: "MemoryData",
    V1.MULTINOMIAL_LOGISTIC_LOSS: "MultinomialLogisticLoss",
    V1.MVN: "MVN",
    V1.POOLING: "Pooling",
    V1.POWER: "Power",
    V1.RELU: "ReLU",
    V1.SIGMOID: "Sigmoid",
    V1.SIGMOID_CROSS_ENTROPY_LOSS: "SigmoidCrossEntropyLoss",
    V1.SILENCE: "Silence",
    V1.SOFTMAX: "Softmax",
    V1.SOFTMAX_LOSS: "SoftmaxWithLoss",
    V1.SPLIT: "Split",
    V1.SLICE: "Slice",
    V1.TANH: "TanH",
    V1.WINDOW_DATA: "WindowData",
    V1.THRESHOLD: "Threshold",
}

# V0 short type name -> V1 enum (reference upgrade_proto.cpp:552
# UpgradeV0LayerType).
V0_TYPE_ENUMS = {
    "accuracy": V1.ACCURACY,
    "bnll": V1.BNLL,
    "concat": V1.CONCAT,
    "conv": V1.CONVOLUTION,
    "data": V1.DATA,
    "dropout": V1.DROPOUT,
    "euclidean_loss": V1.EUCLIDEAN_LOSS,
    "flatten": V1.FLATTEN,
    "hdf5_data": V1.HDF5_DATA,
    "hdf5_output": V1.HDF5_OUTPUT,
    "im2col": V1.IM2COL,
    "images": V1.IMAGE_DATA,
    "infogain_loss": V1.INFOGAIN_LOSS,
    "innerproduct": V1.INNER_PRODUCT,
    "lrn": V1.LRN,
    "multinomial_logistic_loss": V1.MULTINOMIAL_LOGISTIC_LOSS,
    "pool": V1.POOLING,
    "relu": V1.RELU,
    "sigmoid": V1.SIGMOID,
    "softmax": V1.SOFTMAX,
    "softmax_loss": V1.SOFTMAX_LOSS,
    "split": V1.SPLIT,
    "tanh": V1.TANH,
    "window_data": V1.WINDOW_DATA,
}

# Routing of V0 scalar fields into per-type param submessages. Each V0 field
# maps {v0 type name: (submessage attr on V1LayerParameter, field name)}.
# `None` as field name means "repeated: use .append" (the N-d conv fields).
_V0_ROUTES = {
    "num_output": {"conv": ("convolution_param", "num_output"),
                   "innerproduct": ("inner_product_param", "num_output")},
    "biasterm": {"conv": ("convolution_param", "bias_term"),
                 "innerproduct": ("inner_product_param", "bias_term")},
    "weight_filler": {"conv": ("convolution_param", "weight_filler"),
                      "innerproduct": ("inner_product_param", "weight_filler")},
    "bias_filler": {"conv": ("convolution_param", "bias_filler"),
                    "innerproduct": ("inner_product_param", "bias_filler")},
    "pad": {"conv": ("convolution_param", "pad+"),
            "pool": ("pooling_param", "pad")},
    "kernelsize": {"conv": ("convolution_param", "kernel_size+"),
                   "pool": ("pooling_param", "kernel_size")},
    "group": {"conv": ("convolution_param", "group")},
    "stride": {"conv": ("convolution_param", "stride+"),
               "pool": ("pooling_param", "stride")},
    "pool": {"pool": ("pooling_param", "pool")},
    "dropout_ratio": {"dropout": ("dropout_param", "dropout_ratio")},
    "local_size": {"lrn": ("lrn_param", "local_size")},
    "alpha": {"lrn": ("lrn_param", "alpha")},
    "beta": {"lrn": ("lrn_param", "beta")},
    "k": {"lrn": ("lrn_param", "k")},
    "source": {"data": ("data_param", "source"),
               "hdf5_data": ("hdf5_data_param", "source"),
               "images": ("image_data_param", "source"),
               "window_data": ("window_data_param", "source"),
               "infogain_loss": ("infogain_loss_param", "source")},
    "batchsize": {"data": ("data_param", "batch_size"),
                  "hdf5_data": ("hdf5_data_param", "batch_size"),
                  "images": ("image_data_param", "batch_size"),
                  "window_data": ("window_data_param", "batch_size")},
    "rand_skip": {"data": ("data_param", "rand_skip"),
                  "images": ("image_data_param", "rand_skip")},
    "shuffle_images": {"images": ("image_data_param", "shuffle")},
    "new_height": {"images": ("image_data_param", "new_height")},
    "new_width": {"images": ("image_data_param", "new_width")},
    "concat_dim": {"concat": ("concat_param", "concat_dim")},
    "det_fg_threshold": {"window_data": ("window_data_param", "fg_threshold")},
    "det_bg_threshold": {"window_data": ("window_data_param", "bg_threshold")},
    "det_fg_fraction": {"window_data": ("window_data_param", "fg_fraction")},
    "det_context_pad": {"window_data": ("window_data_param", "context_pad")},
    "det_crop_mode": {"window_data": ("window_data_param", "crop_mode")},
}

# V0 fields that always land on transform_param regardless of layer type.
_V0_TRANSFORM_FIELDS = {"scale": "scale", "meanfile": "mean_file",
                        "cropsize": "crop_size", "mirror": "mirror"}

# Message-valued V1 fields to carry over verbatim during V1 -> current
# (everything sharing a name between V1LayerParameter and LayerParameter).
_V1_PARAM_MESSAGES = [
    "accuracy_param", "argmax_param", "concat_param",
    "contrastive_loss_param", "convolution_param", "data_param",
    "dropout_param", "dummy_data_param", "eltwise_param", "exp_param",
    "hdf5_data_param", "hdf5_output_param", "hinge_loss_param",
    "image_data_param", "infogain_loss_param", "inner_product_param",
    "lrn_param", "memory_data_param", "mvn_param", "pooling_param",
    "power_param", "relu_param", "sigmoid_param", "softmax_param",
    "slice_param", "tanh_param", "threshold_param", "window_data_param",
    "transform_param", "loss_param",
]


# ---------------------------------------------------------------------------
# Need-detection predicates (reference upgrade_proto.cpp:15-19).

def net_needs_v0_upgrade(net) -> bool:
    return any(v1.HasField("layer") for v1 in net.layers)


def net_needs_v1_upgrade(net) -> bool:
    return len(net.layers) > 0


# Data-reading V1 layer types with deprecated in-param transform fields.
_DATA_PARAM_ATTRS = {V1.DATA: "data_param", V1.IMAGE_DATA: "image_data_param",
                     V1.WINDOW_DATA: "window_data_param"}
_DEPRECATED_TRANSFORM_FIELDS = ("scale", "mean_file", "crop_size", "mirror")


def net_needs_data_upgrade(net) -> bool:
    for v1 in net.layers:
        attr = _DATA_PARAM_ATTRS.get(v1.type)
        if attr is None:
            continue
        lp = getattr(v1, attr)
        if any(lp.HasField(f) for f in _DEPRECATED_TRANSFORM_FIELDS):
            return True
    return False


def net_needs_input_upgrade(net) -> bool:
    return len(net.input) > 0


def net_needs_batchnorm_upgrade(net) -> bool:
    return any(lp.type == "BatchNorm" and len(lp.param) == 3
               for lp in net.layer)


def net_needs_upgrade(net) -> bool:
    return (net_needs_v0_upgrade(net) or net_needs_v1_upgrade(net)
            or net_needs_data_upgrade(net) or net_needs_input_upgrade(net)
            or net_needs_batchnorm_upgrade(net))


# ---------------------------------------------------------------------------
# V0 -> V1

def _fold_padding_layers(net):
    """V0 nets could express conv padding as a standalone "padding" layer.
    Drop those layers and push their pad value into the consuming conv/pool
    layer, rewiring the consumer's bottom to the padding layer's input
    (reference upgrade_proto.cpp:140 UpgradeV0PaddingLayers)."""
    out = pb.NetParameter()
    out.CopyFrom(net)
    del out.layers[:]
    producer = {name: None for name in net.input}  # blob -> producing V1 entry
    for v1 in net.layers:
        is_padding = v1.layer.type == "padding"
        if not is_padding:
            kept = out.layers.add()
            kept.CopyFrom(v1)
        for j, blob in enumerate(v1.bottom):
            if blob not in producer:
                raise ValueError(f"unknown bottom blob '{blob}'")
            src = producer[blob]
            if src is not None and src.layer.type == "padding":
                if v1.layer.type not in ("conv", "pool"):
                    raise ValueError(
                        "padding layer feeds non-conv/pool layer "
                        f"'{v1.layer.name}' ({v1.layer.type})")
                kept.layer.pad = src.layer.pad
                kept.bottom[j] = src.bottom[0]
        for blob in v1.top:
            producer[blob] = v1
    return out


def _upgrade_v0_layer(v1_in, v1_out) -> bool:
    """One V0 entry -> V1 entry. Returns False when some field could not be
    routed (matching the reference's is_fully_compatible flag)."""
    ok = True
    v1_out.bottom.extend(v1_in.bottom)
    v1_out.top.extend(v1_in.top)
    v0 = v1_in.layer
    if v0.HasField("name"):
        v1_out.name = v0.name
    if v0.HasField("type"):
        enum = V0_TYPE_ENUMS.get(v0.type)
        if enum is None:
            raise ValueError(f"unknown V0 layer type '{v0.type}'")
        v1_out.type = enum
    for b in v0.blobs:
        v1_out.blobs.add().CopyFrom(b)
    v1_out.blobs_lr.extend(v0.blobs_lr)
    v1_out.weight_decay.extend(v0.weight_decay)

    for field, routes in _V0_ROUTES.items():
        if not v0.HasField(field):
            continue
        route = routes.get(v0.type)
        if route is None:
            log.error("V0 field %s is not valid for layer type %s",
                      field, v0.type)
            ok = False
            continue
        sub_attr, target = route
        sub = getattr(v1_out, sub_attr)
        value = getattr(v0, field)
        if field == "pool":  # enum value; same numbering in both schemas
            value = int(value)
        if target.endswith("+"):
            getattr(sub, target[:-1]).append(value)
        elif field in ("weight_filler", "bias_filler"):  # message-valued
            getattr(sub, target).CopyFrom(value)
        else:
            setattr(sub, target, value)

    for field, target in _V0_TRANSFORM_FIELDS.items():
        if v0.HasField(field):
            setattr(v1_out.transform_param, target, getattr(v0, field))
    if v0.HasField("hdf5_output_param"):
        if v0.type == "hdf5_output":
            v1_out.hdf5_output_param.CopyFrom(v0.hdf5_output_param)
        else:
            log.error("hdf5_output_param on layer type %s", v0.type)
            ok = False
    return ok


def upgrade_v0_net(net) -> bool:
    folded = _fold_padding_layers(net)
    upgraded = []
    ok = True
    for v1 in folded.layers:
        nv1 = pb.V1LayerParameter()
        ok &= _upgrade_v0_layer(v1, nv1)
        upgraded.append(nv1)
    del net.layers[:]
    for nv1 in upgraded:
        net.layers.add().CopyFrom(nv1)
    return ok


# ---------------------------------------------------------------------------
# Deprecated per-data-layer transform fields -> transform_param
# (reference upgrade_proto.cpp:662 UpgradeNetDataTransformation).

def upgrade_net_data_transformation(net) -> None:
    for v1 in net.layers:
        attr = _DATA_PARAM_ATTRS.get(v1.type)
        if attr is None:
            continue
        lp = getattr(v1, attr)
        for f in _DEPRECATED_TRANSFORM_FIELDS:
            if lp.HasField(f):
                setattr(v1.transform_param, f, getattr(lp, f))
                lp.ClearField(f)


# ---------------------------------------------------------------------------
# V1 -> current

def _upgrade_v1_layer(v1, lp) -> bool:
    ok = True
    lp.bottom.extend(v1.bottom)
    lp.top.extend(v1.top)
    if v1.HasField("name"):
        lp.name = v1.name
    for r in v1.include:
        lp.include.add().CopyFrom(r)
    for r in v1.exclude:
        lp.exclude.add().CopyFrom(r)
    if v1.HasField("type"):
        lp.type = V1_TYPE_NAMES[v1.type]
    for b in v1.blobs:
        lp.blobs.add().CopyFrom(b)
    # param names / share modes / lr & decay multipliers each extend the
    # ParamSpec list positionally.
    for seq, target in ((v1.param, "name"),
                        (v1.blob_share_mode, "share_mode"),
                        (v1.blobs_lr, "lr_mult"),
                        (v1.weight_decay, "decay_mult")):
        for i, value in enumerate(seq):
            while len(lp.param) <= i:
                lp.param.add()
            setattr(lp.param[i], target, value)
    lp.loss_weight.extend(v1.loss_weight)
    for attr in _V1_PARAM_MESSAGES:
        if v1.HasField(attr):
            getattr(lp, attr).CopyFrom(getattr(v1, attr))
    if v1.HasField("layer"):
        log.error("V1 entry still holds a V0 layer — ignoring it")
        ok = False
    return ok


def upgrade_v1_net(net) -> bool:
    if len(net.layer) > 0:
        raise ValueError(
            "NetParameter mixes 'layers' (V1) and 'layer' (current) fields; "
            "refusing to upgrade an inconsistent definition")
    ok = True
    for v1 in net.layers:
        ok &= _upgrade_v1_layer(v1, net.layer.add())
    del net.layers[:]
    return ok


# ---------------------------------------------------------------------------
# Net-level input fields -> Input layer
# (reference upgrade_proto.cpp:971 UpgradeNetInput).

def upgrade_net_input(net) -> None:
    has_shape = len(net.input_shape) > 0
    has_dim = len(net.input_dim) > 0
    if has_shape or has_dim:
        lp = pb.LayerParameter(name="input", type="Input")
        for i, blob in enumerate(net.input):
            lp.top.append(blob)
            shape = lp.input_param.shape.add()
            if has_shape:
                # Clamp: some hand-written prototxts list fewer shapes than
                # input names, reusing the last shape for the rest.
                shape.CopyFrom(net.input_shape[min(i, len(net.input_shape) - 1)])
            else:
                shape.dim.extend(net.input_dim[4 * i:4 * i + 4])
        # The input layer must come first so its tops exist before use.
        existing = [pb.LayerParameter() for _ in net.layer]
        for dst, src in zip(existing, net.layer):
            dst.CopyFrom(src)
        del net.layer[:]
        net.layer.add().CopyFrom(lp)
        for src in existing:
            net.layer.add().CopyFrom(src)
    # A bare `input` without shapes (legacy caffemodel) is simply dropped.
    del net.input[:]
    del net.input_shape[:]
    del net.input_dim[:]


def upgrade_net_batchnorm(net) -> None:
    """Old BatchNorm definitions declared 3 ParamSpecs (mean/var/bias-count);
    the modern layer owns its statistics and takes none."""
    for lp in net.layer:
        if lp.type == "BatchNorm" and len(lp.param) == 3:
            del lp.param[:]


# ---------------------------------------------------------------------------
# Entry points

def upgrade_net_as_needed(net, source: str = "") -> bool:
    """Migrate `net` in place through every needed upgrade stage. Returns
    False when some legacy field could not be mapped (the net is still
    usable, matching the reference's continue-anyway behavior)."""
    ok = True
    if net_needs_v0_upgrade(net):
        log.info("upgrading V0 (padding-era) net%s",
                 f" from {source}" if source else "")
        ok &= upgrade_v0_net(net)
    if net_needs_data_upgrade(net):
        upgrade_net_data_transformation(net)
    if net_needs_v1_upgrade(net):
        log.info("upgrading V1 'layers' net%s",
                 f" from {source}" if source else "")
        ok &= upgrade_v1_net(net)
    if net_needs_input_upgrade(net):
        upgrade_net_input(net)
    if net_needs_batchnorm_upgrade(net):
        upgrade_net_batchnorm(net)
    return ok


SOLVER_TYPE_NAMES = {
    pb.SolverParameter.SGD: "SGD",
    pb.SolverParameter.NESTEROV: "Nesterov",
    pb.SolverParameter.ADAGRAD: "AdaGrad",
    pb.SolverParameter.RMSPROP: "RMSProp",
    pb.SolverParameter.ADADELTA: "AdaDelta",
    pb.SolverParameter.ADAM: "Adam",
}


def upgrade_solver_as_needed(sp, source: str = "") -> bool:
    """Migrate the deprecated solver_type enum to the string `type` field
    (reference upgrade_proto.cpp:1039 UpgradeSolverType)."""
    if not sp.HasField("solver_type"):
        return True
    if sp.HasField("type"):
        raise ValueError(
            "solver specifies both deprecated solver_type (enum) and type "
            "(string); remove one")
    sp.type = SOLVER_TYPE_NAMES[sp.solver_type]
    sp.ClearField("solver_type")
    log.info("upgraded deprecated solver_type enum%s",
             f" in {source}" if source else "")
    return True

"""Serialization: prototxt text I/O, BlobProto <-> numpy, .caffemodel
weights (reference: src/caffe/util/io.{hpp,cpp}, blob.cpp FromProto/ToProto).

Binary compatibility contract: files written by the reference load here and
vice versa, because the proto schema in ../proto/caffe.proto keeps the
reference's field numbers.
"""
from __future__ import annotations

import numpy as np
from google.protobuf import text_format

from ..proto import pb


def read_proto_text(path: str, message):
    with open(path, "r") as f:
        text_format.Parse(f.read(), message)
    return message


def write_proto_text(path: str, message) -> None:
    with open(path, "w") as f:
        f.write(text_format.MessageToString(message))


def read_proto_binary(path: str, message):
    with open(path, "rb") as f:
        message.ParseFromString(f.read())
    return message


def write_proto_binary(path: str, message) -> None:
    with open(path, "wb") as f:
        f.write(message.SerializeToString())


def read_net_param(path: str) -> "pb.NetParameter":
    """Read a net definition or weights file in any supported format,
    migrating legacy (V0/V1/input-field/...) schemas to the current one
    (reference io.hpp ReadNetParamsFrom{Text,Binary}FileOrDie, which always
    run UpgradeNetAsNeeded)."""
    from .upgrade import upgrade_net_as_needed
    net = pb.NetParameter()
    if path.endswith((".h5", ".hdf5")):
        return read_net_hdf5(path)
    if path.endswith((".caffemodel", ".binaryproto", ".pb")):
        read_proto_binary(path, net)
    else:
        read_proto_text(path, net)
    upgrade_net_as_needed(net, source=path)
    return net


def read_solver_param(path: str) -> "pb.SolverParameter":
    from .upgrade import upgrade_solver_as_needed
    sp = read_proto_text(path, pb.SolverParameter())
    upgrade_solver_as_needed(sp, source=path)
    return sp


def blob_shape(proto: "pb.BlobProto") -> tuple[int, ...]:
    if proto.HasField("shape"):
        return tuple(int(d) for d in proto.shape.dim)
    legacy = (proto.num, proto.channels, proto.height, proto.width)
    return tuple(int(d) for d in legacy)


def blob_to_array(proto: "pb.BlobProto") -> np.ndarray:
    shape = blob_shape(proto)
    if len(proto.double_data):
        arr = np.asarray(proto.double_data, dtype=np.float64)
    else:
        arr = np.asarray(proto.data, dtype=np.float32)
    return arr.reshape(shape)


def array_to_blob(arr, proto: "pb.BlobProto | None" = None) -> "pb.BlobProto":
    if proto is None:
        proto = pb.BlobProto()
    arr = np.asarray(arr)
    proto.shape.dim[:] = arr.shape
    proto.ClearField("data")
    proto.ClearField("double_data")
    if arr.dtype == np.float64:
        proto.double_data.extend(arr.reshape(-1).tolist())
    else:
        proto.data.extend(np.asarray(arr, dtype=np.float32).reshape(-1).tolist())
    return proto


def read_blob_from_file(path: str) -> np.ndarray:
    """Read a single serialized BlobProto (e.g. a mean file or an infogain
    H matrix, reference io.hpp ReadProtoFromBinaryFile + Blob::FromProto)."""
    return blob_to_array(read_proto_binary(path, pb.BlobProto()))


# ---------------------------------------------------------------------------
# HDF5 snapshot formats (reference: net.cpp:883-930 ToHDF5 layout
# /data/<layer>/<param_index>, net.cpp:821-860 CopyTrainedLayersFromHDF5;
# sgd_solver.cpp:283-356 solver state fields iter/learned_net/current_step +
# /history/<i>).

def write_net_hdf5(net_param: "pb.NetParameter", path: str,
                   write_diff: bool = False) -> None:
    import h5py
    with h5py.File(path, "w") as f:
        data = f.create_group("data")
        for lp in net_param.layer:
            g = data.create_group(lp.name)
            for i, b in enumerate(lp.blobs):
                g.create_dataset(str(i), data=blob_to_array(b))


def read_net_hdf5(path: str) -> "pb.NetParameter":
    import h5py
    out = pb.NetParameter()
    with h5py.File(path, "r") as f:
        for name in f["data"]:
            lp = out.layer.add()
            lp.name = name
            g = f["data"][name]
            for i in sorted(g, key=int):
                array_to_blob(np.asarray(g[i]), lp.blobs.add())
    return out


def write_solver_state_hdf5(path: str, iteration: int, learned_net: str,
                            current_step: int, history) -> None:
    import h5py
    with h5py.File(path, "w") as f:
        f.create_dataset("iter", data=np.int64(iteration))
        f.create_dataset("learned_net",
                         data=np.bytes_(learned_net.encode()))
        f.create_dataset("current_step", data=np.int64(current_step))
        g = f.create_group("history")
        for i, arr in enumerate(history):
            g.create_dataset(str(i), data=np.asarray(arr))


def read_solver_state_hdf5(path: str):
    import h5py
    with h5py.File(path, "r") as f:
        it = int(np.asarray(f["iter"]))
        learned = np.asarray(f["learned_net"]).item()
        if isinstance(learned, bytes):
            learned = learned.decode()
        cur = int(np.asarray(f["current_step"]))
        g = f["history"]
        hist = [np.asarray(g[i]) for i in sorted(g, key=int)]
    return it, learned, cur, hist

"""Serialization: prototxt text I/O, BlobProto <-> numpy, .caffemodel
weights (reference: src/caffe/util/io.{hpp,cpp}, blob.cpp FromProto/ToProto).

Binary compatibility contract: files written by the reference load here and
vice versa, because the proto schema in ../proto/caffe.proto keeps the
reference's field numbers.
"""
from __future__ import annotations

import numpy as np
from google.protobuf import text_format

from ..proto import pb


def read_proto_text(path: str, message):
    with open(path, "r") as f:
        text_format.Parse(f.read(), message)
    return message


def write_proto_text(path: str, message) -> None:
    with open(path, "w") as f:
        f.write(text_format.MessageToString(message))


def read_proto_binary(path: str, message):
    with open(path, "rb") as f:
        message.ParseFromString(f.read())
    return message


def write_proto_binary(path: str, message) -> None:
    with open(path, "wb") as f:
        f.write(message.SerializeToString())


def read_net_param(path: str) -> "pb.NetParameter":
    net = pb.NetParameter()
    if path.endswith((".caffemodel", ".binaryproto", ".pb")):
        return read_proto_binary(path, net)
    return read_proto_text(path, net)


def read_solver_param(path: str) -> "pb.SolverParameter":
    return read_proto_text(path, pb.SolverParameter())


def blob_shape(proto: "pb.BlobProto") -> tuple[int, ...]:
    if proto.HasField("shape"):
        return tuple(int(d) for d in proto.shape.dim)
    legacy = (proto.num, proto.channels, proto.height, proto.width)
    return tuple(int(d) for d in legacy)


def blob_to_array(proto: "pb.BlobProto") -> np.ndarray:
    shape = blob_shape(proto)
    if len(proto.double_data):
        arr = np.asarray(proto.double_data, dtype=np.float64)
    else:
        arr = np.asarray(proto.data, dtype=np.float32)
    return arr.reshape(shape)


def array_to_blob(arr, proto: "pb.BlobProto | None" = None) -> "pb.BlobProto":
    if proto is None:
        proto = pb.BlobProto()
    arr = np.asarray(arr)
    proto.shape.dim[:] = arr.shape
    proto.ClearField("data")
    proto.ClearField("double_data")
    if arr.dtype == np.float64:
        proto.double_data.extend(arr.reshape(-1).tolist())
    else:
        proto.data.extend(np.asarray(arr, dtype=np.float32).reshape(-1).tolist())
    return proto


def read_blob_from_file(path: str) -> np.ndarray:
    """Read a single serialized BlobProto (e.g. a mean file or an infogain
    H matrix, reference io.hpp ReadProtoFromBinaryFile + Blob::FromProto)."""
    return blob_to_array(read_proto_binary(path, pb.BlobProto()))

"""Loss, softmax, and evaluation layers (reference: src/caffe/layers/
{softmax,softmax_loss,euclidean_loss,sigmoid_cross_entropy_loss,
multinomial_logistic_loss,infogain_loss,hinge_loss,contrastive_loss,
accuracy}_layer.*).

Loss layers return scalar tops; the net sums loss_weight * top into the
objective that jax.grad differentiates — replacing the reference's
hand-written Backward_cpu/gpu of each loss.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import Layer, register_layer
from ..proto import pb

_LOG_MIN = 1e-20  # kLOG_THRESHOLD in the reference losses
_FLT_MIN = np.finfo(np.float32).tiny


def _softmax(x, axis):
    x = x - jax.lax.stop_gradient(jnp.max(x, axis=axis, keepdims=True))
    e = jnp.exp(x)
    return e / jnp.sum(e, axis=axis, keepdims=True)


def _stable(x):
    """Upcast to >= f32 for log/exp/large reductions: loss layers stay
    numerically f32 even when the net runs a bf16 compute_dtype (a ~1e-2
    relative loss error otherwise). No-op for f32/f64 inputs."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


class _LossLayer(Layer):
    """Base: first top defaults to loss_weight 1 (reference loss_layer.cpp:9)."""

    auto_top_blobs = True

    def default_loss_weight(self, top_index: int) -> float:
        return 1.0 if top_index == 0 else 0.0


@register_layer("Softmax")
class SoftmaxLayer(Layer):
    def setup(self, bottom_shapes):
        self.axis = self.lp.softmax_param.axis % len(bottom_shapes[0])
        self.top_shapes = [tuple(bottom_shapes[0])]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        return [_softmax(bottoms[0], self.axis)], None


def _loss_normalizer(mode, outer, spatial, valid_count):
    """Reference softmax_loss_layer.cpp:70-91 get_normalizer."""
    if mode == pb.LossParameter.FULL:
        n = float(outer * spatial)
    elif mode == pb.LossParameter.VALID:
        n = valid_count  # may be a traced array
    elif mode == pb.LossParameter.BATCH_SIZE:
        n = float(outer)
    else:  # NONE
        n = 1.0
    return jnp.maximum(n, 1.0)


def _normalization_mode(loss_param):
    # legacy `normalize` overrides (softmax_loss_layer.cpp:40-47)
    if loss_param.HasField("normalize"):
        return (pb.LossParameter.VALID if loss_param.normalize
                else pb.LossParameter.BATCH_SIZE)
    return loss_param.normalization


@register_layer("SoftmaxWithLoss")
class SoftmaxWithLossLayer(_LossLayer):
    """Fused softmax + multinomial logistic loss with ignore_label and the
    four normalization modes (reference softmax_loss_layer.cpp)."""

    def setup(self, bottom_shapes):
        sp = self.lp.softmax_param
        self.axis = sp.axis % len(bottom_shapes[0])
        lp = self.lp.loss_param
        self.ignore_label = lp.ignore_label if lp.HasField("ignore_label") else None
        self.norm_mode = _normalization_mode(lp)
        self.top_shapes = [()]
        if len(self.lp.top) > 1:
            self.top_shapes.append(tuple(bottom_shapes[0]))
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x, labels = _stable(bottoms[0]), bottoms[1]
        prob = _softmax(x, self.axis)
        # move class axis last; remaining dims are outer x spatial positions
        pm = jnp.moveaxis(prob, self.axis, -1)
        lab = labels.reshape(pm.shape[:-1]).astype(jnp.int32)
        p_true = jnp.take_along_axis(pm, lab[..., None], axis=-1)[..., 0]
        nll = -jnp.log(jnp.maximum(p_true, _FLT_MIN))
        outer = x.shape[0]
        spatial = int(np.prod(x.shape[:self.axis] + x.shape[self.axis + 1:])) // outer
        if self.ignore_label is not None:
            mask = (lab != self.ignore_label)
            nll = jnp.where(mask, nll, 0.0)
            valid = jnp.sum(mask).astype(x.dtype)
        else:
            valid = float(outer * spatial)
        norm = _loss_normalizer(self.norm_mode, outer, spatial, valid)
        loss = jnp.sum(nll) / norm
        tops = [loss]
        if len(self.top_shapes) > 1:
            tops.append(prob)
        return tops, None


@register_layer("EuclideanLoss")
class EuclideanLossLayer(_LossLayer):
    """sum((a-b)^2) / (2 * batch) (reference euclidean_loss_layer.cpp:20-27)."""

    def setup(self, bottom_shapes):
        a, b = bottom_shapes[0], bottom_shapes[1]
        # reference euclidean_loss_layer.cpp:12 CHECK_EQ on the per-sample
        # count; silent numpy broadcasting (or a total-count-only check
        # letting (8,3) pair with (4,6)) would mix samples across entries
        if a[0] != b[0] or int(np.prod(a[1:])) != int(np.prod(b[1:])):
            raise ValueError(
                f"EuclideanLoss {self.name!r}: inputs must agree in batch "
                f"size and per-sample count, got {a} vs {b}")
        self.num = bottom_shapes[0][0]
        self.top_shapes = [()]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        d = (_stable(bottoms[0])
             - _stable(bottoms[1]).reshape(bottoms[0].shape))
        return [jnp.sum(d * d) / (2.0 * self.num)], None


@register_layer("SigmoidCrossEntropyLoss")
class SigmoidCrossEntropyLossLayer(_LossLayer):
    """Stable fused sigmoid + per-element CE, normalized by batch size
    (reference sigmoid_cross_entropy_loss_layer.cpp:40-56)."""

    def setup(self, bottom_shapes):
        self.num = bottom_shapes[0][0]
        self.top_shapes = [()]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x, t = _stable(bottoms[0]), _stable(bottoms[1])
        # loss_ij = x*(t-1) ... using the reference's stable form:
        # x - x*t + log(1+exp(-x)) for x>=0 ; -x*t + log(1+exp(x)) otherwise
        per = (jnp.maximum(x, 0) - x * t
               + jnp.log1p(jnp.exp(-jnp.abs(x))))
        return [jnp.sum(per) / self.num], None


@register_layer("MultinomialLogisticLoss")
class MultinomialLogisticLossLayer(_LossLayer):
    """-mean log p[label]; input is already a probability distribution
    (reference multinomial_logistic_loss_layer.cpp:28-43)."""

    def setup(self, bottom_shapes):
        self.num = bottom_shapes[0][0]
        self.top_shapes = [()]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        p, labels = _stable(bottoms[0]), bottoms[1]
        lab = labels.reshape(-1).astype(jnp.int32)
        p_true = p.reshape(self.num, -1)[jnp.arange(self.num), lab]
        return [-jnp.sum(jnp.log(jnp.maximum(p_true, _LOG_MIN))) / self.num], None


@register_layer("InfogainLoss")
class InfogainLossLayer(_LossLayer):
    """-mean sum_j H[label, j] log p_j; H from file or third bottom
    (reference infogain_loss_layer.cpp)."""

    def setup(self, bottom_shapes):
        self.num = bottom_shapes[0][0]
        self.H = None
        if len(bottom_shapes) < 3:
            from ..utils.io import read_blob_from_file
            ip = self.lp.infogain_loss_param
            assert ip.source, "InfogainLoss needs an H matrix source or bottom"
            self.H = jnp.asarray(read_blob_from_file(ip.source))
        self.top_shapes = [()]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        p, labels = _stable(bottoms[0]), bottoms[1]
        H = bottoms[2] if len(bottoms) > 2 else self.H
        H = H.reshape(H.shape[-2], H.shape[-1]) if H.ndim > 2 else H
        lab = labels.reshape(-1).astype(jnp.int32)
        logp = jnp.log(jnp.maximum(p.reshape(self.num, -1), _LOG_MIN))
        rows = jnp.take(H, lab, axis=0)
        return [-jnp.sum(rows * logp) / self.num], None


@register_layer("HingeLoss")
class HingeLossLayer(_LossLayer):
    """One-vs-all hinge on raw scores (reference hinge_loss_layer.cpp:17-45)."""

    def setup(self, bottom_shapes):
        self.num = bottom_shapes[0][0]
        self.norm = self.lp.hinge_loss_param.norm
        self.top_shapes = [()]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x, labels = _stable(bottoms[0]), bottoms[1]
        flat = x.reshape(self.num, -1)
        lab = labels.reshape(-1).astype(jnp.int32)
        sign = 1.0 - 2.0 * jax.nn.one_hot(lab, flat.shape[1], dtype=flat.dtype)
        margins = jnp.maximum(0.0, 1.0 + sign * flat)
        if self.norm == pb.HingeLossParameter.L2:
            return [jnp.sum(margins * margins) / self.num], None
        return [jnp.sum(margins) / self.num], None


@register_layer("ContrastiveLoss")
class ContrastiveLossLayer(_LossLayer):
    """Siamese contrastive loss (reference contrastive_loss_layer.cpp:40-64)."""

    def setup(self, bottom_shapes):
        self.num = bottom_shapes[0][0]
        clp = self.lp.contrastive_loss_param
        self.margin = clp.margin
        self.legacy = clp.legacy_version
        self.top_shapes = [()]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        a, b, y = _stable(bottoms[0]), _stable(bottoms[1]), bottoms[2]
        d = (a - b).reshape(self.num, -1)
        dist_sq = jnp.sum(d * d, axis=1)
        y = y.reshape(-1).astype(a.dtype)
        if self.legacy:
            dissim = jnp.maximum(self.margin - dist_sq, 0.0)
        else:
            dist = jnp.sqrt(jnp.maximum(dist_sq, 1e-12))
            dissim = jnp.square(jnp.maximum(self.margin - dist, 0.0))
        loss = jnp.sum(y * dist_sq + (1.0 - y) * dissim)
        return [loss / (2.0 * self.num)], None


@register_layer("Accuracy")
class AccuracyLayer(Layer):
    """Top-k accuracy with ignore_label and optional per-class top
    (reference accuracy_layer.cpp). Non-differentiable by design — it is an
    evaluation output, never part of the training objective."""

    def setup(self, bottom_shapes):
        ap = self.lp.accuracy_param
        self.top_k = ap.top_k
        self.axis = ap.axis % len(bottom_shapes[0])
        self.ignore_label = (ap.ignore_label if ap.HasField("ignore_label")
                             else None)
        self.num_classes = bottom_shapes[0][self.axis]
        self.top_shapes = [()]
        if len(self.lp.top) > 1:
            self.top_shapes.append((self.num_classes,))
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x, labels = bottoms[0], bottoms[1]
        xm = jnp.moveaxis(x, self.axis, -1)
        lab = labels.reshape(xm.shape[:-1]).astype(jnp.int32)
        score_true = jnp.take_along_axis(xm, lab[..., None], axis=-1)
        # label counts among top_k: position is correct if fewer than top_k
        # classes score strictly higher than the true class (matches the
        # reference's sort-then-scan within ties being benign for k=1).
        higher = jnp.sum(xm > score_true, axis=-1)
        correct = (higher < self.top_k)
        if self.ignore_label is not None:
            mask = (lab != self.ignore_label)
            count = jnp.maximum(jnp.sum(mask), 1)
            acc = jnp.sum(jnp.where(mask, correct, False)) / count
        else:
            mask = jnp.ones_like(correct, dtype=bool)
            count = correct.size
            acc = jnp.mean(correct.astype(x.dtype))
        tops = [lax_stop(acc)]
        if len(self.top_shapes) > 1:
            valid = jnp.where(mask, 1.0, 0.0)
            per_hit = jnp.zeros(self.num_classes).at[lab.reshape(-1)].add(
                (correct & mask).reshape(-1).astype(x.dtype))
            per_cnt = jnp.zeros(self.num_classes).at[lab.reshape(-1)].add(
                valid.reshape(-1))
            tops.append(lax_stop(per_hit / jnp.maximum(per_cnt, 1.0)))
        return tops, None


def lax_stop(x):
    return jax.lax.stop_gradient(x)

"""SPP, Filter, and Python layers (reference: src/caffe/layers/
spp_layer.cpp, filter_layer.cpp, include/caffe/layers/python_layer.hpp).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import Layer, register_layer
from ..proto import pb
from .vision import PoolingLayer


@register_layer("SPP")
class SPPLayer(Layer):
    """Spatial pyramid pooling (spp_layer.cpp): pyramid_height levels, level
    l pools into 2^l x 2^l bins (kernel = ceil(dim/bins), stride = kernel,
    pad = (remainder+1)//2 — spp_layer.cpp:22-42), each level flattened and
    all concatenated. Implemented exactly as the reference does: internal
    PoolingLayers per level."""

    def setup(self, bottom_shapes):
        spp = self.lp.spp_param
        n, c, h, w = bottom_shapes[0]
        self.levels = []
        total = 0
        for l in range(spp.pyramid_height):
            bins = 2 ** l
            lp = pb.LayerParameter(name=f"{self.name}_pool{l}",
                                   type="Pooling")
            lp.top.append("t")
            pp = lp.pooling_param
            pp.pool = {pb.SPPParameter.MAX: pb.PoolingParameter.MAX,
                       pb.SPPParameter.AVE: pb.PoolingParameter.AVE,
                       pb.SPPParameter.STOCHASTIC:
                           pb.PoolingParameter.STOCHASTIC}[spp.pool]
            pp.kernel_h = math.ceil(h / bins)
            pp.kernel_w = math.ceil(w / bins)
            pp.stride_h = pp.kernel_h
            pp.stride_w = pp.kernel_w
            pp.pad_h = (pp.kernel_h * bins - h + 1) // 2
            pp.pad_w = (pp.kernel_w * bins - w + 1) // 2
            pool = PoolingLayer(lp, self.phase)
            out = pool.setup([bottom_shapes[0]])[0]
            assert out[2] == bins and out[3] == bins, \
                f"SPP level {l}: got {out[2:]} bins, want {bins}"
            self.levels.append(pool)
            total += c * bins * bins
        self.top_shapes = [(n, total)]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        n = x.shape[0]
        parts = []
        for pool in self.levels:
            tops_l, _ = pool.apply([], [x], ctx)
            parts.append(tops_l[0].reshape(n, -1))
        return [jnp.concatenate(parts, axis=1)], None


@register_layer("Filter")
class FilterLayer(Layer):
    """Batch-item filtering by a selector blob (filter_layer.cpp: forwards
    only items whose selector is nonzero).

    XLA deviation (documented): the reference emits a *dynamically sized*
    batch; under jit all shapes are static, so the selected items are
    packed to the front of a full-size batch and the remainder zero-filled.
    Downstream consumers can read the count from the selector sum. This
    preserves the selected items' values and order.

    CAVEAT: a loss/Accuracy layer fed directly from Filter output
    normalizes over the full padded batch, so its value diverges from the
    reference's dynamically shrunk batch by a factor of n_keep/batch.
    Route Filter output through computation whose per-item values you
    consume (the reference examples do), or rescale the loss host-side by
    batch/n_keep using the selector sum.
    """

    def setup(self, bottom_shapes):
        # last bottom is the selector (N,) or (N,1)
        self.top_shapes = [tuple(s) for s in bottom_shapes[:-1]]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        sel = bottoms[-1].reshape(bottoms[-1].shape[0])
        keep = sel != 0
        # stable pack-to-front permutation: indices of kept items first
        order = jnp.argsort(~keep, stable=True)
        n_keep = jnp.sum(keep)
        tops = []
        for b in bottoms[:-1]:
            packed = b[order]
            mask_shape = (b.shape[0],) + (1,) * (b.ndim - 1)
            valid = (jnp.arange(b.shape[0]) < n_keep).reshape(mask_shape)
            tops.append(jnp.where(valid, packed, 0))
        return tops, None


@register_layer("Python")
class PythonLayer(Layer):
    """User-extensible layer (python_layer.hpp:14): prototxt
    `type: "Python"` with python_param {module, layer, param_str}
    instantiates a user class with Caffe's setup/reshape/forward contract.

    The user object receives pycaffe-style bottom/top wrappers with mutable
    numpy `.data`/`.diff`. Forward runs host-side through jax.pure_callback
    wrapped in jax.custom_vjp: the backward pass calls the user object's
    `backward(top, propagate_down, bottom)` host-side (python_layer.hpp:40
    delegates exactly so), reading the filled bottom `.diff`s. A user class
    without a `backward` method contributes zero gradients, matching a
    user-side no-op Backward in the reference."""

    def setup(self, bottom_shapes):
        import importlib
        ppar = self.lp.python_param
        module = importlib.import_module(ppar.module)
        cls = getattr(module, ppar.layer)
        self.obj = cls()
        self.obj.param_str = ppar.param_str

        class _B:
            def __init__(self, shape):
                self.data = np.zeros(shape, np.float32)
                self.diff = np.zeros(shape, np.float32)
                self._shape = list(shape)

            def reshape(self, *shape):
                self._shape = list(shape)
                self.data = np.zeros(shape, np.float32)
                self.diff = np.zeros(shape, np.float32)

            @property
            def shape(self):
                return self._shape

            def count(self):
                return self.data.size

        bottoms = [_B(s) for s in bottom_shapes]
        n_top = max(len(self.lp.top), 1)
        tops = [_B((1,)) for _ in range(n_top)]
        self.obj.setup(bottoms, tops)
        self.obj.reshape(bottoms, tops)
        self._B = _B
        self.bottom_shapes = [tuple(s) for s in bottom_shapes]
        self.top_shapes = [tuple(t.shape) for t in tops]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        def host_forward(*arrs):
            bs = [self._B(a.shape) for a in arrs]
            for b, a in zip(bs, arrs):
                b.data[...] = np.asarray(a)
            ts = [self._B(s) for s in self.top_shapes]
            self.obj.reshape(bs, ts)
            self.obj.forward(bs, ts)
            return tuple(np.asarray(t.data, np.float32) for t in ts)

        def host_backward(*arrs):
            """arrs = bottom datas + top diffs; returns bottom diffs."""
            n_b = len(self.bottom_shapes)
            bs = [self._B(a.shape) for a in arrs[:n_b]]
            for b, a in zip(bs, arrs[:n_b]):
                b.data[...] = np.asarray(a)
            ts = [self._B(s) for s in self.top_shapes]
            self.obj.reshape(bs, ts)
            # Caffe calls Backward on the same object right after Forward;
            # user layers legitimately cache forward state (e.g. the stock
            # pyloss example caches self.diff). Replay forward on these
            # bottoms so that cached state is fresh before backward runs.
            self.obj.forward(bs, ts)
            for t, g in zip(ts, arrs[n_b:]):
                t.diff[...] = np.asarray(g)
            self.obj.backward(ts, [True] * n_b, bs)
            return tuple(np.asarray(b.diff, np.float32) for b in bs)

        if not any(isinstance(b, jax.core.Tracer) for b in bottoms):
            # eager path: run host-side directly — works on backends with
            # no host-callback support (e.g. tunneled PJRT plugins)
            return [jnp.asarray(t) for t in host_forward(*bottoms)], None

        out_spec = tuple(jax.ShapeDtypeStruct(s, jnp.float32)
                         for s in self.top_shapes)
        in_spec = tuple(jax.ShapeDtypeStruct(s, jnp.float32)
                        for s in self.bottom_shapes)
        has_backward = callable(getattr(self.obj, "backward", None))

        @jax.custom_vjp
        def run(*bs):
            return jax.pure_callback(host_forward, out_spec, *bs)

        def run_fwd(*bs):
            return run(*bs), bs

        def run_bwd(saved_bottoms, top_diffs):
            if not has_backward:
                return tuple(jnp.zeros(s, jnp.float32)
                             for s in self.bottom_shapes)
            return jax.pure_callback(host_backward, in_spec,
                                     *saved_bottoms, *top_diffs)

        run.defvjp(run_fwd, run_bwd)
        tops = run(*[b.astype(jnp.float32) for b in bottoms])
        return list(tops), None

    def default_loss_weight(self, top_index: int):
        # honor loss_weight from the prototxt only (layer.hpp default)
        return 0.0

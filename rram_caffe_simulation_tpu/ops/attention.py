"""Multi-head self-attention layer — TPU framework extension (the
reference has no attention anywhere; SURVEY §5.7). Prototxt surface:

    layer {
      name: "attn" type: "Attention" bottom: "x" top: "y"
      attention_param { num_heads: 8 causal: true }
    }

over a (N, S, E) bottom. Parameters are a fused QKV in-projection
(3E x E + bias) and an out-projection (E x E + bias), stored in Caffe's
(out, in) orientation so `.caffemodel` round-trips like every other
layer. The core attention math lives in parallel/sequence.py; under a
mesh with a "seq" axis the same layer computation can be sharded with
ring_attention_sharded / ulysses_attention_sharded (tested equal to this
single-device path in tests/test_sequence_parallel.py).
"""
import jax
import jax.numpy as jnp

from ..core.fillers import make_filler
from ..core.registry import Layer, register_layer
from ..proto import pb


@register_layer("Attention")
class AttentionLayer(Layer):

    def setup(self, bottom_shapes):
        ap = self.lp.attention_param
        n, s, e = bottom_shapes[0]
        self.heads = max(int(ap.num_heads), 1)
        if e % self.heads:
            raise ValueError(
                f"Attention embed dim {e} not divisible by num_heads "
                f"{self.heads} (layer {self.name!r})")
        self.causal = bool(ap.causal)
        self.embed = e
        self.top_shapes = [(n, s, e)]
        return self.top_shapes

    def num_params(self):
        return 4  # qkv weight, qkv bias, out weight, out bias

    def init_params(self, key):
        ap = self.lp.attention_param
        if ap.HasField("weight_filler"):
            wf = make_filler(ap.weight_filler)
        else:
            wf = make_filler(pb.FillerParameter(type="xavier"))
        bf = make_filler(ap.bias_filler if ap.HasField("bias_filler")
                         else pb.FillerParameter(type="constant"))
        k1, k2, k3, k4 = jax.random.split(key, 4)
        e = self.embed
        return [wf(k1, (3 * e, e)), bf(k2, (3 * e,)),
                wf(k3, (e, e)), bf(k4, (e,))]

    def apply(self, params, bottoms, ctx):
        from ..parallel.sequence import attention
        x = bottoms[0]
        n, s, e = x.shape
        h = self.heads
        w_qkv, b_qkv, w_out, b_out = params
        qkv = jnp.einsum("nse,fe->nsf", x, w_qkv) + b_qkv
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def split_heads(t):  # (N, S, E) -> (N, H, S, E/H)
            return t.reshape(n, s, h, e // h).transpose(0, 2, 1, 3)

        if ctx.seq_mesh is not None:
            # sequence parallelism (Solver.enable_sequence_parallel):
            # the S axis shards over the mesh and K/V ride the ring (or
            # two all_to_alls for ulysses) — parallel/sequence.py
            from ..parallel.sequence import (ring_attention_sharded,
                                             ulysses_attention_sharded)
            fn = (ring_attention_sharded if ctx.seq_impl == "ring"
                  else ulysses_attention_sharded)
            o = fn(split_heads(q), split_heads(k), split_heads(v),
                   ctx.seq_mesh, axis=ctx.seq_axis, causal=self.causal)
        else:
            o = attention(split_heads(q), split_heads(k), split_heads(v),
                          causal=self.causal)
        o = o.transpose(0, 2, 1, 3).reshape(n, s, e)
        return [jnp.einsum("nse,fe->nsf", o, w_out) + b_out], None

"""Elementwise neuron layers (reference: src/caffe/layers/{relu,prelu,elu,
sigmoid,tanh,absval,bnll,power,exp,log,threshold,dropout}_layer.*).

All are trivially fused by XLA into neighboring matmuls/convs — the manual
CUDA kernels of the reference collapse into jnp expressions.
"""
from __future__ import annotations

import math
import zlib

import jax
import jax.numpy as jnp

from ..core.fillers import make_filler
from ..core.registry import Layer, register_layer
from ..proto import pb


class _Elementwise(Layer):
    def setup(self, bottom_shapes):
        self.top_shapes = [tuple(bottom_shapes[0])]
        return self.top_shapes


@register_layer("ReLU")
class ReLULayer(_Elementwise):
    def setup(self, bottom_shapes):
        self.negative_slope = self.lp.relu_param.negative_slope
        return super().setup(bottom_shapes)

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        if self.negative_slope:
            return [jnp.where(x > 0, x, self.negative_slope * x)], None
        return [jnp.maximum(x, 0)], None


@register_layer("PReLU")
class PReLULayer(_Elementwise):
    """Learnable per-channel slope (reference prelu_layer.cpp)."""

    def setup(self, bottom_shapes):
        pp = self.lp.prelu_param
        self.channel_shared = pp.channel_shared
        self.channels = bottom_shapes[0][1]
        return super().setup(bottom_shapes)

    def num_params(self):
        return 1

    def init_params(self, key):
        shape = (1,) if self.channel_shared else (self.channels,)
        pp = self.lp.prelu_param
        if pp.HasField("filler"):
            return [make_filler(pp.filler)(key, shape)]
        # explicit f32 (default dtype is f64 under x64)
        return [jnp.full(shape, 0.25, jnp.float32)]

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        a = params[0]
        if not self.channel_shared:
            a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
        return [jnp.where(x > 0, x, a * x)], None


@register_layer("ELU")
class ELULayer(_Elementwise):
    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        alpha = self.lp.elu_param.alpha
        return [jnp.where(x > 0, x, alpha * (jnp.exp(jnp.minimum(x, 0)) - 1))], None


@register_layer("Sigmoid")
class SigmoidLayer(_Elementwise):
    def apply(self, params, bottoms, ctx):
        return [jax.nn.sigmoid(bottoms[0])], None


@register_layer("TanH")
class TanHLayer(_Elementwise):
    def apply(self, params, bottoms, ctx):
        return [jnp.tanh(bottoms[0])], None


@register_layer("AbsVal")
class AbsValLayer(_Elementwise):
    def apply(self, params, bottoms, ctx):
        return [jnp.abs(bottoms[0])], None


@register_layer("BNLL")
class BNLLLayer(_Elementwise):
    """log(1 + exp(x)), computed stably (reference bnll_layer.cpp:10-25)."""

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        return [jnp.maximum(x, 0) + jnp.log1p(jnp.exp(-jnp.abs(x)))], None


@register_layer("Power")
class PowerLayer(_Elementwise):
    """(shift + scale * x) ^ power (reference power_layer.cpp)."""

    def apply(self, params, bottoms, ctx):
        pp = self.lp.power_param
        y = pp.shift + pp.scale * bottoms[0]
        if pp.power != 1.0:
            y = jnp.power(y, pp.power)
        return [y], None


@register_layer("Exp")
class ExpLayer(_Elementwise):
    """base^(shift + scale*x); base -1 means e (reference exp_layer.cpp)."""

    def apply(self, params, bottoms, ctx):
        ep = self.lp.exp_param
        inner = ep.shift + ep.scale * bottoms[0]
        if ep.base == -1.0:
            return [jnp.exp(inner)], None
        return [jnp.exp(inner * math.log(ep.base))], None


@register_layer("Log")
class LogLayer(_Elementwise):
    """log_base(shift + scale*x) (reference log_layer.cpp)."""

    def apply(self, params, bottoms, ctx):
        lp = self.lp.log_param
        inner = lp.shift + lp.scale * bottoms[0]
        y = jnp.log(inner)
        if lp.base != -1.0:
            y = y / math.log(lp.base)
        return [y], None


@register_layer("Threshold")
class ThresholdLayer(_Elementwise):
    def apply(self, params, bottoms, ctx):
        t = self.lp.threshold_param.threshold
        return [(bottoms[0] > t).astype(bottoms[0].dtype)], None


@register_layer("Dropout")
class DropoutLayer(_Elementwise):
    """Inverted dropout: scale by 1/(1-ratio) at train, identity at test
    (reference dropout_layer.cpp:30-60)."""

    def setup(self, bottom_shapes):
        self.ratio = self.lp.dropout_param.dropout_ratio
        return super().setup(bottom_shapes)

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        if self.phase != pb.TRAIN or self.ratio == 0.0:
            return [x], None
        assert ctx.rng is not None, "Dropout in TRAIN needs a PRNG key"
        # crc32, not hash(): PYTHONHASHSEED randomizes hash() per process,
        # which would break cross-process reproducibility of fault sweeps.
        key = jax.random.fold_in(
            ctx.rng, zlib.crc32(self.name.encode()) & 0x7FFFFFFF)
        keep = jax.random.bernoulli(key, 1.0 - self.ratio, x.shape)
        return [jnp.where(keep, x / (1.0 - self.ratio), 0.0).astype(x.dtype)], None

"""Data/input layers (reference: src/caffe/layers/{base_data,data,image_data,
hdf5_data,hdf5_output,memory_data,window_data,dummy_data,input}_layer.*).

Design: in the functional graph, data-source layers declare top names and
static shapes; actual batches are produced by the host pipeline
(rram_caffe_simulation_tpu.data) and passed into Net.apply as a dict. This
replaces the reference's 3-thread DataReader -> prefetch -> Forward_cpu
pipeline (data_reader.cpp:73, base_data_layer.cpp:76-120) with a host-side
iterator plus async jax.device_put. DummyData stays a traced generator so
nets using it need no external input.
"""
from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core.fillers import make_filler
from ..core.registry import Layer, register_layer
from ..proto import pb


class DataSourceLayer(Layer):
    """Base for layers whose tops come from the host pipeline."""

    is_data_source = True

    def setup(self, bottom_shapes):
        self.top_shapes = self.output_shapes()
        return self.top_shapes

    def output_shapes(self):
        raise NotImplementedError

    def apply(self, params, bottoms, ctx):
        raise RuntimeError(
            f"{self.type_name} tops must be fed via the batch dict")


@register_layer("Input")
class InputLayer(DataSourceLayer):
    def output_shapes(self):
        shapes = [tuple(int(d) for d in s.dim)
                  for s in self.lp.input_param.shape]
        n_top = len(self.lp.top)
        if len(shapes) == 1 and n_top > 1:
            shapes = shapes * n_top
        assert len(shapes) == n_top, "Input needs one shape per top"
        return shapes


@register_layer("Data")
class DataLayer(DataSourceLayer):
    """LMDB/LevelDB-backed Datum stream (reference data_layer.cpp). Shapes
    are inferred from the first record + transform_param, like
    DataTransformer::InferBlobShape (data_transformer.cpp:100)."""

    def output_shapes(self):
        from ..data.db import infer_datum_shape
        dp = self.lp.data_param
        c, h, w = infer_datum_shape(dp.source, dp.backend)
        crop = self.lp.transform_param.crop_size
        if crop > 0:
            h = w = crop
        n = dp.batch_size
        shapes = [(n, c, h, w)]
        if len(self.lp.top) > 1:
            shapes.append((n,))
        return shapes


@register_layer("ImageData")
class ImageDataLayer(DataSourceLayer):
    """File-list image stream (reference image_data_layer.cpp)."""

    def output_shapes(self):
        from ..data.image import infer_image_shape
        ip = self.lp.image_data_param
        c, h, w = infer_image_shape(ip)
        crop = self.lp.transform_param.crop_size
        if crop > 0:
            h = w = crop
        n = ip.batch_size
        shapes = [(n, c, h, w)]
        if len(self.lp.top) > 1:
            shapes.append((n,))
        return shapes


@register_layer("HDF5Data")
class HDF5DataLayer(DataSourceLayer):
    """HDF5 dataset stream; tops are named datasets in file order
    (reference hdf5_data_layer.cpp)."""

    def output_shapes(self):
        import h5py
        hp = self.lp.hdf5_data_param
        with open(hp.source) as f:
            first = f.readline().strip()
        shapes = []
        with h5py.File(first, "r") as h5:
            for top in self.lp.top:
                ds = h5[top]
                shapes.append((hp.batch_size,) + tuple(ds.shape[1:]))
        return shapes


@register_layer("MemoryData")
class MemoryDataLayer(DataSourceLayer):
    """In-memory arrays fed from the API (reference memory_data_layer.cpp)."""

    def output_shapes(self):
        mp = self.lp.memory_data_param
        n = mp.batch_size
        return [(n, mp.channels, mp.height, mp.width), (n,)]


@register_layer("WindowData")
class WindowDataLayer(DataSourceLayer):
    """R-CNN window crops (reference window_data_layer.cpp)."""

    def output_shapes(self):
        wp = self.lp.window_data_param
        crop = self.lp.transform_param.crop_size or wp.crop_size
        assert crop > 0, "WindowData requires crop_size"
        return [(wp.batch_size, 3, crop, crop), (wp.batch_size,)]


@register_layer("DummyData")
class DummyDataLayer(Layer):
    """Filler-generated tops, traced in-graph (reference
    dummy_data_layer.cpp). Constant fillers refill every step exactly like
    the reference's `refill_` logic; random fillers draw from ctx.rng."""

    is_data_source = False  # generates its tops inside the traced graph

    def setup(self, bottom_shapes):
        dp = self.lp.dummy_data_param
        n_top = len(self.lp.top)
        if dp.shape:
            shapes = [tuple(int(d) for d in s.dim) for s in dp.shape]
        else:
            shapes = [(dp.num[i], dp.channels[i], dp.height[i], dp.width[i])
                      for i in range(len(dp.num))]
        if len(shapes) == 1 and n_top > 1:
            shapes = shapes * n_top
        fillers = list(dp.data_filler)
        if not fillers:
            default = pb.FillerParameter()
            fillers = [default] * n_top
        elif len(fillers) == 1 and n_top > 1:
            fillers = fillers * n_top
        self.fillers = [make_filler(f) for f in fillers]
        self.filler_types = [f.type for f in fillers]
        self.top_shapes = shapes[:n_top]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        tops = []
        for i, (fill, shape) in enumerate(zip(self.fillers, self.top_shapes)):
            if self.filler_types[i] == "constant":
                key = jax.random.PRNGKey(0)
            else:
                assert ctx.rng is not None, \
                    "random DummyData fillers need a PRNG key"
                key = jax.random.fold_in(
                    ctx.rng,
                    (zlib.crc32(self.name.encode()) + i) & 0x7FFFFFFF)
            tops.append(fill(key, shape))
        if ctx.compute_dtype is not None:
            # generated float data must match the cast params (mixed
            # precision): external batches are cast by the solver, but
            # in-graph fillers draw f32 by default
            tops = [t.astype(ctx.compute_dtype)
                    if jnp.issubdtype(t.dtype, jnp.floating) else t
                    for t in tops]
        return tops, None


@register_layer("HDF5Output")
class HDF5OutputLayer(Layer):
    """Sink layer persisting its two bottoms to an HDF5 file, written
    host-side through an ordered io_callback during forward (reference
    hdf5_output_layer.cpp:30-74 writes synchronously in Forward_cpu).

    Deviation (documented): the reference re-saves only the latest batch
    to the `data`/`label` datasets; here successive forwards APPEND rows
    (resizable datasets), which is what feature-extraction consumers
    actually want. The file is truncated at layer construction."""

    def setup(self, bottom_shapes):
        import os
        self.file_name = self.lp.hdf5_output_param.file_name
        if self.file_name and os.path.exists(self.file_name):
            os.remove(self.file_name)
        self.top_shapes = []
        return []

    def _save(self, data, label):
        import h5py
        with h5py.File(self.file_name, "a") as f:
            for name, arr in (("data", np.asarray(data)),
                              ("label", np.asarray(label))):
                if name in f:
                    ds = f[name]
                    n0 = ds.shape[0]
                    ds.resize(n0 + arr.shape[0], axis=0)
                    ds[n0:] = arr
                else:
                    f.create_dataset(name, data=arr,
                                     maxshape=(None,) + arr.shape[1:])

    def apply(self, params, bottoms, ctx):
        # Concrete (eager) inputs write synchronously on the host, like
        # the reference's Forward_cpu — also the only path that works on
        # remote-compile transports where host-callback programs cannot
        # lower (the axon tunnel hangs compiling io_callback). Traced
        # inputs keep the io_callback so the layer composes under jit.
        if not any(isinstance(b, jax.core.Tracer) for b in bottoms):
            self._save(np.asarray(bottoms[0]), np.asarray(bottoms[1]))
            return [], None
        from jax.experimental import io_callback
        # stop_gradient keeps the callback out of the autodiff graph (the
        # reference Backward is a no-op)
        io_callback(self._save, None,
                    jax.lax.stop_gradient(bottoms[0]),
                    jax.lax.stop_gradient(bottoms[1]), ordered=True)
        return [], None

"""Common compute/structural layers (reference: src/caffe/layers/
{inner_product,eltwise,concat,slice,flatten,reshape,split,silence,tile,bias,
scale,embed,reduction,argmax,batch_reindex,filter,parameter}_layer.*).

InnerProductLayer is the RRAM fault target in the reference (net.cpp:482-493
collects its params into failure_learnable_params_); here the net builder
does the same bookkeeping over this registry's `fault_target` flag.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.fillers import make_filler
from ..core.registry import Layer, register_layer
from ..proto import pb
from ._util import flat_shape_from


@register_layer("InnerProduct")
class InnerProductLayer(Layer):
    """Fully connected: y = x . W^T (+ b). Reference
    inner_product_layer.cpp:84-139. Weight shape (num_output, K) exactly as
    Caffe stores it, so .caffemodel weights load without transposition."""

    fault_target = True  # reference net.cpp:485: InnerProduct params are
    # the RRAM failure-prone set

    def setup(self, bottom_shapes):
        ip = self.lp.inner_product_param
        self.num_output = ip.num_output
        self.bias_term = ip.bias_term
        self.transpose = ip.transpose
        self.axis = ip.axis % len(bottom_shapes[0])
        outer, inner = flat_shape_from(bottom_shapes[0], self.axis)
        self.K = inner
        self.weight_shape = ((self.K, self.num_output) if self.transpose
                             else (self.num_output, self.K))
        self.out_shape = tuple(bottom_shapes[0][:self.axis]) + (self.num_output,)
        self.top_shapes = [self.out_shape]
        return self.top_shapes

    def num_params(self):
        return 2 if self.bias_term else 1

    def init_params(self, key):
        ip = self.lp.inner_product_param
        kw, kb = jax.random.split(key)
        params = [make_filler(ip.weight_filler)(kw, self.weight_shape)]
        if self.bias_term:
            params.append(make_filler(ip.bias_filler)(kb, (self.num_output,)))
        return params

    def apply(self, params, bottoms, ctx):
        x = bottoms[0].reshape((-1, self.K))
        w = params[0]
        cb = getattr(ctx, "crossbar", None)
        cb = cb.get(self.name) if cb else None
        # Tiled crossbar mapping (fault/mapping.py via ctx.tiles): this
        # layer's weight spans multiple physical arrays, so its read is
        # per-tile ADC-quantized partial sums accumulated across the
        # K-tile axis. (tr, tc) are the tile cell dims over the STORED
        # weight; the crossbar (K, N) view swaps them under the default
        # Caffe (num_output, K) layout.
        tl = getattr(ctx, "tiles", None)
        tl = tl.get(self.name) if tl else None
        adc = getattr(ctx, "adc_bits", 0)
        kernel_tiles = None
        if tl is not None:
            tr, tc = tl
            bk, bn = (tr, tc) if self.transpose else (tc, tr)
            kernel_tiles = (int(bk), int(bn), int(adc))
        if cb is not None:
            # Fused Pallas crossbar read: stuck mask + conductance noise
            # + optional ADC-grid quantization + matmul in one kernel,
            # noise drawn and the grid applied in VMEM (never in HBM).
            # broken/stuck are shaped like the STORED weight. Under the
            # sweep's config vmap this dispatches to the config-batched
            # kernel (fault/hw_aware.py ENGINE MATRIX). A tiled layer
            # folds its tile grid + per-tile ADC into the kernel
            # (block grid == tile grid).
            from ..fault.hw_aware import crossbar_matmul
            broken, stuck, seed, sigma, q_bits = cb[:5]
            # optional 6th element: the config-sharded mesh the sweep's
            # batched kernel dispatch shard_maps over (ISSUE 13)
            shard_mesh = cb[5] if len(cb) > 5 else None
            y = crossbar_matmul(
                x.astype(jnp.float32),
                (w if self.transpose else w.T).astype(jnp.float32),
                broken if self.transpose else broken.T,
                (stuck if self.transpose else stuck.T).astype(jnp.float32),
                seed, sigma, q_bits,
                kernel_tiles, shard_mesh).astype(bottoms[0].dtype)
        elif kernel_tiles is not None:
            # jax engine, tiled: the stored weight already carries the
            # perturbed/faulty read values (the solver installs them);
            # this layer owns the partial-sum structure + per-tile ADC.
            from ..fault.hw_aware import tiled_crossbar_matmul
            y = tiled_crossbar_matmul(
                x, w if self.transpose else w.T, kernel_tiles[0],
                kernel_tiles[1], kernel_tiles[2],
                preferred_element_type=bottoms[0].dtype)
        else:
            y = jnp.dot(x, w if self.transpose else w.T,
                        preferred_element_type=bottoms[0].dtype)
        if adc and tl is None:
            # Hardware-aware ADC: the crossbar's bitline currents (the
            # matmul output, pre-bias — the bias lives in digital) are
            # read through a adc_bits-wide converter. A TILED layer has
            # already paid its ADC per tile-column partial sum — the
            # whole-output converter would double-quantize.
            from ..fault.hw_aware import quantize_ste
            y = quantize_ste(y, adc)
        if self.bias_term:
            y = y + params[1]
        return [y.reshape(self.out_shape[:-1] + (self.num_output,))], None


@register_layer("Embed")
class EmbedLayer(Layer):
    """Lookup-table forward of one-hot InnerProduct (reference
    embed_layer.cpp). Weight shape (input_dim, num_output)."""

    def setup(self, bottom_shapes):
        ep = self.lp.embed_param
        self.num_output = ep.num_output
        self.input_dim = ep.input_dim
        self.bias_term = ep.bias_term
        self.top_shapes = [tuple(bottom_shapes[0]) + (self.num_output,)]
        return self.top_shapes

    def num_params(self):
        return 2 if self.bias_term else 1

    def init_params(self, key):
        ep = self.lp.embed_param
        kw, kb = jax.random.split(key)
        params = [make_filler(ep.weight_filler)(
            kw, (self.input_dim, self.num_output))]
        if self.bias_term:
            params.append(make_filler(ep.bias_filler)(kb, (self.num_output,)))
        return params

    def apply(self, params, bottoms, ctx):
        ids = bottoms[0].astype(jnp.int32)
        y = jnp.take(params[0], ids, axis=0)
        if self.bias_term:
            y = y + params[1]
        return [y], None


@register_layer("Eltwise")
class EltwiseLayer(Layer):
    """PROD / SUM(coeff) / MAX over k bottoms (reference eltwise_layer.cpp)."""

    def setup(self, bottom_shapes):
        ep = self.lp.eltwise_param
        self.op = ep.operation
        self.coeffs = list(ep.coeff) or [1.0] * len(bottom_shapes)
        assert len(self.coeffs) == len(bottom_shapes)
        self.top_shapes = [tuple(bottom_shapes[0])]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        if self.op == pb.EltwiseParameter.PROD:
            y = bottoms[0]
            for b in bottoms[1:]:
                y = y * b
        elif self.op == pb.EltwiseParameter.SUM:
            y = self.coeffs[0] * bottoms[0]
            for c, b in zip(self.coeffs[1:], bottoms[1:]):
                y = y + c * b
        else:  # MAX
            y = bottoms[0]
            for b in bottoms[1:]:
                y = jnp.maximum(y, b)
        return [y], None


@register_layer("Concat")
class ConcatLayer(Layer):
    def setup(self, bottom_shapes):
        cp = self.lp.concat_param
        self.axis = (cp.axis if cp.HasField("axis") or not cp.HasField("concat_dim")
                     else cp.concat_dim) % len(bottom_shapes[0])
        out = list(bottom_shapes[0])
        out[self.axis] = sum(s[self.axis] for s in bottom_shapes)
        self.top_shapes = [tuple(out)]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        return [jnp.concatenate(bottoms, axis=self.axis)], None


@register_layer("Slice")
class SliceLayer(Layer):
    def setup(self, bottom_shapes):
        sp = self.lp.slice_param
        self.axis = (sp.axis if sp.HasField("axis") or not sp.HasField("slice_dim")
                     else sp.slice_dim) % len(bottom_shapes[0])
        total = bottom_shapes[0][self.axis]
        n_top = len(self.lp.top)
        points = list(sp.slice_point)
        if points:
            assert len(points) == n_top - 1
            bounds = [0] + points + [total]
        else:
            assert total % n_top == 0
            step = total // n_top
            bounds = list(range(0, total + 1, step))
        self.sections = bounds[1:-1]
        self.top_shapes = []
        for i in range(n_top):
            s = list(bottom_shapes[0])
            s[self.axis] = bounds[i + 1] - bounds[i]
            self.top_shapes.append(tuple(s))
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        return list(jnp.split(bottoms[0], self.sections, axis=self.axis)), None


@register_layer("Split")
class SplitLayer(Layer):
    """Fan a blob to k consumers. In the functional graph this is a pure copy
    (autodiff sums gradients automatically, which was the entire purpose of
    the reference's InsertSplits rewrite, util/insert_splits.cpp:12)."""

    def setup(self, bottom_shapes):
        self.top_shapes = [tuple(bottom_shapes[0])] * len(self.lp.top)
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        return [bottoms[0]] * len(self.top_shapes), None


@register_layer("Silence")
class SilenceLayer(Layer):
    def setup(self, bottom_shapes):
        self.top_shapes = []
        return []

    def apply(self, params, bottoms, ctx):
        return [], None


@register_layer("Flatten")
class FlattenLayer(Layer):
    def setup(self, bottom_shapes):
        fp = self.lp.flatten_param
        s = bottom_shapes[0]
        a = fp.axis % len(s)
        e = fp.end_axis % len(s)
        mid = int(np.prod(s[a:e + 1]))
        self.out_shape = tuple(s[:a]) + (mid,) + tuple(s[e + 1:])
        self.top_shapes = [self.out_shape]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        return [bottoms[0].reshape(self.out_shape)], None


@register_layer("Reshape")
class ReshapeLayer(Layer):
    """Reference reshape_layer.cpp: dims of 0 copy the bottom dim, one -1
    infers; axis/num_axes restrict the replaced span."""

    def setup(self, bottom_shapes):
        rp = self.lp.reshape_param
        s = list(bottom_shapes[0])
        a = rp.axis % (len(s) + 1) if rp.axis < 0 else rp.axis
        n = len(s) - a if rp.num_axes == -1 else rp.num_axes
        spec = list(rp.shape.dim)
        new_mid = []
        for i, d in enumerate(spec):
            if d == 0:
                new_mid.append(s[a + i])
            else:
                new_mid.append(int(d))
        total_in = int(np.prod(s[a:a + n])) if n > 0 else 1
        if -1 in new_mid:
            known = int(np.prod([d for d in new_mid if d != -1]))
            new_mid[new_mid.index(-1)] = total_in // known
        self.out_shape = tuple(s[:a]) + tuple(new_mid) + tuple(s[a + n:])
        self.top_shapes = [self.out_shape]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        return [bottoms[0].reshape(self.out_shape)], None


@register_layer("Tile")
class TileLayer(Layer):
    def setup(self, bottom_shapes):
        tp = self.lp.tile_param
        self.axis = tp.axis % len(bottom_shapes[0])
        self.tiles = tp.tiles
        out = list(bottom_shapes[0])
        out[self.axis] *= self.tiles
        self.top_shapes = [tuple(out)]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        reps = [1] * bottoms[0].ndim
        reps[self.axis] = self.tiles
        return [jnp.tile(bottoms[0], reps)], None


@register_layer("Bias")
class BiasLayer(Layer):
    """Add a (possibly learned) bias broadcast over trailing axes
    (reference bias_layer.cpp)."""

    def setup(self, bottom_shapes):
        bp = self.lp.bias_param
        self.learned = len(bottom_shapes) == 1
        s = bottom_shapes[0]
        if self.learned:
            axis = bp.axis % len(s)
            num_axes = bp.num_axes
            if num_axes == -1:
                self.bias_shape = tuple(s[axis:])
            else:
                self.bias_shape = tuple(s[axis:axis + num_axes])
            self.axis = axis
        else:
            self.bias_shape = tuple(bottom_shapes[1])
            # find alignment axis: bias shape matches s[axis:axis+len]
            self.axis = bp.axis % len(s)
        self.bcast = ([1] * self.axis + list(self.bias_shape)
                      + [1] * (len(s) - self.axis - len(self.bias_shape)))
        self.top_shapes = [tuple(s)]
        return self.top_shapes

    def num_params(self):
        return 1 if self.learned else 0

    def init_params(self, key):
        if not self.learned:
            return []
        return [make_filler(self.lp.bias_param.filler)(key, self.bias_shape)]

    def apply(self, params, bottoms, ctx):
        b = params[0] if self.learned else bottoms[1]
        return [bottoms[0] + b.reshape(self.bcast)], None


@register_layer("Scale")
class ScaleLayer(Layer):
    """Multiply by a (possibly learned) scale, with optional bias — the
    affine half of Caffe BatchNorm+Scale pairs (reference scale_layer.cpp)."""

    def setup(self, bottom_shapes):
        sp = self.lp.scale_param
        self.learned = len(bottom_shapes) == 1
        self.bias_term = sp.bias_term
        s = bottom_shapes[0]
        axis = sp.axis % len(s)
        if self.learned:
            if sp.num_axes == -1:
                self.scale_shape = tuple(s[axis:])
            else:
                self.scale_shape = tuple(s[axis:axis + sp.num_axes])
        else:
            self.scale_shape = tuple(bottom_shapes[1])
        self.axis = axis
        self.bcast = ([1] * axis + list(self.scale_shape)
                      + [1] * (len(s) - axis - len(self.scale_shape)))
        self.top_shapes = [tuple(s)]
        return self.top_shapes

    def num_params(self):
        n = 1 if self.learned else 0
        if self.bias_term:
            n += 1
        return n

    def init_params(self, key):
        sp = self.lp.scale_param
        ks, kb = jax.random.split(key)
        params = []
        if self.learned:
            # Caffe defaults the scale filler to 1 when unset
            # (scale_layer.cpp:39-47).
            if sp.HasField("filler"):
                params.append(make_filler(sp.filler)(ks, self.scale_shape))
            else:
                # explicit f32: default dtype would be f64 under x64,
                # poisoning downstream conv dtypes
                params.append(jnp.ones(self.scale_shape, jnp.float32))
        if self.bias_term:
            params.append(make_filler(sp.bias_filler)(kb, self.scale_shape))
        return params

    def apply(self, params, bottoms, ctx):
        if self.learned:
            scale = params[0]
            bias = params[1] if self.bias_term else None
        else:
            scale = bottoms[1]
            bias = params[0] if self.bias_term else None
        y = bottoms[0] * scale.reshape(self.bcast)
        if bias is not None:
            y = y + bias.reshape(self.bcast)
        return [y], None


@register_layer("Reduction")
class ReductionLayer(Layer):
    """SUM/ASUM/SUMSQ/MEAN over trailing axes (reference
    reduction_layer.cpp)."""

    def setup(self, bottom_shapes):
        rp = self.lp.reduction_param
        self.op = rp.operation
        self.coeff = rp.coeff
        s = bottom_shapes[0]
        self.axis = rp.axis % len(s)
        self.top_shapes = [tuple(s[:self.axis])]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        flat = x.reshape(self.top_shapes[0] + (-1,))
        if self.op == pb.ReductionParameter.SUM:
            y = jnp.sum(flat, axis=-1)
        elif self.op == pb.ReductionParameter.ASUM:
            y = jnp.sum(jnp.abs(flat), axis=-1)
        elif self.op == pb.ReductionParameter.SUMSQ:
            y = jnp.sum(flat * flat, axis=-1)
        else:  # MEAN
            y = jnp.mean(flat, axis=-1)
        return [y * self.coeff], None


@register_layer("ArgMax")
class ArgMaxLayer(Layer):
    def setup(self, bottom_shapes):
        ap = self.lp.argmax_param
        self.top_k = ap.top_k
        self.out_max_val = ap.out_max_val
        self.has_axis = ap.HasField("axis")
        s = bottom_shapes[0]
        if self.has_axis:
            self.axis = ap.axis % len(s)
            out = list(s)
            out[self.axis] = self.top_k
            self.top_shapes = [tuple(out)]
        else:
            # legacy layout: (N, 1|2, top_k, 1)
            ch = 2 if self.out_max_val else 1
            self.top_shapes = [(s[0], ch, self.top_k, 1)]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        if self.has_axis:
            xm = jnp.moveaxis(x, self.axis, -1)
            vals, idx = jax.lax.top_k(xm, self.top_k)
            out = vals if self.out_max_val else idx.astype(x.dtype)
            return [jnp.moveaxis(out, -1, self.axis)], None
        flat = x.reshape(x.shape[0], -1)
        vals, idx = jax.lax.top_k(flat, self.top_k)
        idxf = idx.astype(x.dtype).reshape(x.shape[0], 1, self.top_k, 1)
        if self.out_max_val:
            valsf = vals.reshape(x.shape[0], 1, self.top_k, 1)
            return [jnp.concatenate([idxf, valsf], axis=1)], None
        return [idxf], None


@register_layer("BatchReindex")
class BatchReindexLayer(Layer):
    """Gather batch items by an index bottom (reference
    batch_reindex_layer.cpp). Output batch size must be static, so it comes
    from the index bottom's shape."""

    def setup(self, bottom_shapes):
        self.n_out = bottom_shapes[1][0]
        self.top_shapes = [(self.n_out,) + tuple(bottom_shapes[0][1:])]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        idx = bottoms[1].reshape(-1).astype(jnp.int32)
        return [jnp.take(bottoms[0], idx, axis=0)], None


@register_layer("Parameter")
class ParameterLayer(Layer):
    """Expose a learnable blob as a top (reference parameter_layer.hpp)."""

    def setup(self, bottom_shapes):
        self.shape = tuple(self.lp.parameter_param.shape.dim)
        self.top_shapes = [self.shape]
        return self.top_shapes

    def num_params(self):
        return 1

    def init_params(self, key):
        # explicit f32 (default dtype is f64 under x64)
        return [jnp.zeros(self.shape, jnp.float32)]

    def apply(self, params, bottoms, ctx):
        return [params[0]], None

"""Vision layers: Convolution, Deconvolution, Pooling, LRN, BatchNorm, MVN,
Crop, Im2col (reference: src/caffe/layers/{base_conv,conv,deconv,pooling,lrn,
batch_norm,mvn,crop,im2col}_layer.*).

TPU design notes: Caffe lowers conv to im2col+GEMM by hand; here convolution
is a single `lax.conv_general_dilated`, which XLA tiles directly onto the MXU
— the entire im2col machinery (util/im2col.*) is subsumed. Blob layout keeps
Caffe's NCHW semantics; XLA assigns physical TPU layouts itself.

Tiled crossbar mapping (ISSUE 18): when `LayerContext.tiles` names a
Convolution layer, its forward recovers Caffe's im2col+GEMM framing
explicitly — `lax.conv_general_dilated_patches` rows against the
flattened `(K, N) = (C_in/g*kh*kw, C_out)` weight view — so the GEMM
can route through the same per-tile ADC crossbar read the InnerProduct
path uses (fault/hw_aware.py `crossbar_matmul` on the pallas engine,
`tiled_crossbar_matmul` on the jax engine).

The patch OPERAND MODE (ISSUE 19) is `LayerContext.conv_im2col`
(threaded from `Solver(conv_im2col=)` / `SweepRunner(conv_im2col=)`;
the `RRAM_CONV_IM2COL` env var remains the fallback for hand-built
contexts), one of:

- ``premat`` (default): the (N*OH*OW, C_in*kh*kw) patch matrix is
  materialized once per forward. Both engines.
- ``tilewise``: lazy per-K-tile slab extraction inside the jax
  engine's tile loop (bit-identical values — patch extraction is an
  exact gather — lower peak memory, re-extracted per tile). On the
  pallas engine the solver resolves it to premat with a recorded
  reason (the kernel already streams (bm, bk) slabs through VMEM).
- ``implicit``: the patch matrix never exists in HBM. The pallas
  engine gathers each (bm, bk) operand block IN-KERNEL from the raw
  padded activation (`crossbar_conv_matmul`, fault/hw_aware.py); the
  jax engine gathers each K-tile slab through the same precomputed
  additive address plan (fault/mapping.py `im2col_index_plan`). Both
  bit-identical to premat; backward replays the premat patches-based
  VJP (v1 — the engine resolution records the note).

An un-named conv layer traces the exact pre-PR `conv_general_dilated`
program.
"""
from __future__ import annotations

import functools
import os
import zlib

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.fillers import make_filler
from ..core.registry import Layer, register_layer
from ..proto import pb
from ._util import (ave_pool_divisors, ceil_pad_hi, conv_spatial_params,
                    pool_spatial_params, pooled_size)

DIMNUMS_2D = ("NCHW", "OIHW", "NCHW")


class _BaseConv(Layer):
    """Shared setup for Convolution/Deconvolution
    (reference base_conv_layer.cpp:17-200)."""

    deconv = False

    def setup(self, bottom_shapes):
        cp = self.lp.convolution_param
        assert cp.axis == 1, "only channel axis 1 is supported"
        self.kernel, self.stride, self.pad, self.dilation = conv_spatial_params(cp)
        self.num_output = cp.num_output
        self.group = cp.group
        self.bias_term = cp.bias_term
        n, c = bottom_shapes[0][:2]
        spatial = bottom_shapes[0][2:]
        assert c % self.group == 0 and self.num_output % self.group == 0
        if self.deconv:
            # weight shape is (input_channels, num_output/group, kh, kw)
            self.weight_shape = (c, self.num_output // self.group) + self.kernel
            out_spatial = tuple(
                self.stride[i] * (spatial[i] - 1)
                + (self.dilation[i] * (self.kernel[i] - 1) + 1) - 2 * self.pad[i]
                for i in range(len(spatial)))
        else:
            self.weight_shape = (self.num_output, c // self.group) + self.kernel
            out_spatial = tuple(
                (spatial[i] + 2 * self.pad[i]
                 - (self.dilation[i] * (self.kernel[i] - 1) + 1))
                // self.stride[i] + 1
                for i in range(len(spatial)))
        self.in_channels = c
        for s in bottom_shapes[1:]:
            assert tuple(s) == tuple(bottom_shapes[0]), \
                f"{self.name}: all conv bottoms must share a shape"
        n_top = max(1, len(self.lp.top))
        self.top_shapes = [(n, self.num_output) + out_spatial] * n_top
        return self.top_shapes

    def num_params(self):
        return 2 if self.bias_term else 1

    def init_params(self, key):
        cp = self.lp.convolution_param
        kw, kb = jax.random.split(key)
        weight = make_filler(cp.weight_filler)(kw, self.weight_shape)
        params = [weight]
        if self.bias_term:
            params.append(make_filler(cp.bias_filler)(kb, (self.num_output,)))
        return params


# Grouped convs with group <= this unroll into per-group convs + concat
# (identical math): XLA:TPU lowers the grouped WEIGHT-gradient conv
# through batch_group_count, measured ~10x off the MXU path — AlexNet's
# group-2 training went 555 -> 7,063 img/s with the split form (round
# 3). Beyond the threshold (depthwise-style group counts) the unroll
# would explode compile time, and XLA special-cases true depthwise, so
# feature_group_count stays.
_GROUP_SPLIT_MAX = 4


def _grouped_conv(conv, x, w, group):
    """Apply `conv(x, w)` with Caffe group semantics: unrolled
    per-group convs + concat under _GROUP_SPLIT_MAX, XLA
    feature_group_count beyond."""
    if 1 < group <= _GROUP_SPLIT_MAX:
        xs = jnp.split(x, group, axis=1)
        ws = jnp.split(w, group, axis=0)
        return jnp.concatenate(
            [conv(a, b) for a, b in zip(xs, ws)], axis=1)
    return conv(x, w, feature_group_count=group)


@register_layer("Convolution")
class ConvolutionLayer(_BaseConv):
    """reference conv_layer.cpp + base_conv_layer.cpp (im2col+GEMM with
    groups) -> XLA convolution; small group counts unroll into
    per-group convs + concat (see _GROUP_SPLIT_MAX), larger ones use
    feature_group_count. Under a tile mapping (`ctx.tiles` names this
    layer) the forward is the explicit im2col GEMM routed through the
    tiled crossbar read instead — see the module docstring."""

    def _conv(self, x, w):
        conv = functools.partial(
            lax.conv_general_dilated,
            window_strides=self.stride,
            padding=[(p, p) for p in self.pad],
            rhs_dilation=self.dilation,
            dimension_numbers=DIMNUMS_2D,
            preferred_element_type=x.dtype)
        return _grouped_conv(conv, x, w, self.group)

    def _out_hw(self, x):
        return tuple(
            (x.shape[2 + i] + 2 * self.pad[i]
             - (self.dilation[i] * (self.kernel[i] - 1) + 1))
            // self.stride[i] + 1
            for i in range(len(self.kernel)))

    def _patch_rows(self, x, c0=0, c1=None):
        """im2col rows of bottom channels [c0, c1): a
        (N*OH*OW, (c1-c0)*kh*kw) matrix in channel-major feature order
        — index c*(kh*kw) + spatial — matching the stored weight's
        `w.reshape(C_out, -1)` flatten, so rows @ view is exactly the
        conv. HIGHEST precision: the one-hot extraction conv must
        reproduce activation values bit-exactly (TPU's default MXU
        precision rounds f32 operands through bf16), keeping the
        premat and tilewise operands byte-identical."""
        xs = x if c0 == 0 and (c1 is None or c1 == x.shape[1]) \
            else x[:, c0:c1]
        p = lax.conv_general_dilated_patches(
            xs, filter_shape=self.kernel, window_strides=self.stride,
            padding=[(p, p) for p in self.pad],
            rhs_dilation=self.dilation,
            dimension_numbers=DIMNUMS_2D,
            precision=lax.Precision.HIGHEST)
        n_, f, oh, ow = p.shape
        return p.transpose(0, 2, 3, 1).reshape(n_ * oh * ow, f)

    def _crossbar_conv(self, x, w, ctx, tl, cb):
        """The tiled crossbar read of this conv layer: im2col patch
        rows against the flattened (K, N) = (C_in*kh*kw, C_out) weight
        view, per-(K, N)-tile ADC partial sums accumulated across the
        K-tile (input-patch) axis — the InnerProduct read structure
        over the im2col view. `tl` = (bk, bn) tile cell dims over the
        view (solver._tiles_ctx); `cb` = the pallas-engine crossbar
        context (broken/stuck in STORED layout, reshaped here to the
        view the kernel's block grid tiles) or None for the jax engine
        (the stored weight already carries the perturbed/faulty read
        values the solver installed)."""
        if self.group != 1:
            raise ValueError(
                f"layer {self.name!r}: grouped convolution "
                f"(group={self.group}) is not mappable onto the im2col "
                "crossbar view — each group is a separate GEMM and the "
                "tile grid would straddle group boundaries; train this "
                "layer untiled (tile_spec='1x1') or ungrouped")
        bk, bn = int(tl[0]), int(tl[1])
        adc = int(getattr(ctx, "adc_bits", 0) or 0)
        n = x.shape[0]
        oh, ow = self._out_hw(x)
        wv = w.reshape(w.shape[0], -1).T  # (K, C_out) im2col view
        mode = getattr(ctx, "conv_im2col", None)
        if not mode:
            mode = os.environ.get("RRAM_CONV_IM2COL",
                                  "premat").strip().lower() or "premat"
        if mode not in ("premat", "tilewise", "implicit"):
            raise ValueError(
                f"RRAM_CONV_IM2COL / conv_im2col={mode!r}: expected "
                "'premat' (pre-materialized patch operand), 'tilewise' "
                "(lazy per-K-tile slab extraction, jax engine) or "
                "'implicit' (in-kernel / plan-driven patch gather)")
        if cb is not None and mode == "implicit":
            # Implicit-im2col Pallas read: the raw NCHW activation goes
            # straight to the kernel, which gathers each (bm, bk)
            # operand block via the static address plan — the patch
            # matrix never exists in HBM (fault/hw_aware.py).
            from ..fault.hw_aware import crossbar_conv_matmul
            from ..fault.mapping import conv_geom, to_im2col
            broken, stuck, seed, sigma, q_bits = cb[:5]
            shard_mesh = cb[5] if len(cb) > 5 else None
            geom = conv_geom(self.kernel, self.stride, self.pad,
                             self.dilation)
            y = crossbar_conv_matmul(
                x.astype(jnp.float32), wv.astype(jnp.float32),
                to_im2col(broken),
                to_im2col(stuck).astype(jnp.float32),
                seed, sigma, q_bits, (bk, bn, adc), geom,
                shard_mesh).astype(x.dtype)
        elif cb is not None:
            # Fused Pallas crossbar read (one launch per shard under
            # the sweep's config vmap / shard_map — the custom_vmap
            # seam in fault/hw_aware.py): the patch operand is
            # pre-materialized (mode "tilewise" lands here too — the
            # solver records that resolution — since the kernel's
            # BlockSpec already streams (bm, bk) slabs through VMEM).
            from ..fault.hw_aware import crossbar_matmul
            from ..fault.mapping import to_im2col
            broken, stuck, seed, sigma, q_bits = cb[:5]
            shard_mesh = cb[5] if len(cb) > 5 else None
            xm = self._patch_rows(x)
            y = crossbar_matmul(
                xm.astype(jnp.float32), wv.astype(jnp.float32),
                to_im2col(broken),
                to_im2col(stuck).astype(jnp.float32),
                seed, sigma, q_bits, (bk, bn, adc),
                shard_mesh).astype(x.dtype)
        elif mode == "implicit":
            # jax-engine implicit: plan-driven K-tile slab gather from
            # the flat padded activation — same address plan as the
            # kernel, fed to the lazy-operand tiled read. Gathers are
            # exact, so every slab is byte-identical to the premat
            # operand's columns.
            from ..fault.hw_aware import tiled_crossbar_matmul_slabs
            from ..fault.mapping import (conv_geom, im2col_index_plan,
                                         pad_activation_flat)
            geom = conv_geom(self.kernel, self.stride, self.pad,
                             self.dilation)
            rb_np, co_np, _, _, _ = im2col_index_plan(x.shape, geom)
            xflat = pad_activation_flat(x, geom)
            rb = jnp.asarray(rb_np)
            co = jnp.asarray(co_np)

            def slab(k0, k1):
                return xflat[rb[:, None] + co[None, k0:k1]]

            y = tiled_crossbar_matmul_slabs(
                slab, wv, bk, bn, adc, n * oh * ow,
                preferred_element_type=x.dtype)
        elif mode == "tilewise":
            from ..fault.hw_aware import tiled_crossbar_matmul_slabs
            khw = self.kernel[0] * self.kernel[1]

            def slab(k0, k1):
                # extract only the channels covering view rows
                # [k0, k1) and slice the overhang — an exact gather,
                # so every column is byte-identical to the premat
                # operand's
                ch0, ch1 = k0 // khw, -(-k1 // khw)
                rows = self._patch_rows(x, ch0, ch1)
                return rows[:, k0 - ch0 * khw:k1 - ch0 * khw]

            y = tiled_crossbar_matmul_slabs(
                slab, wv, bk, bn, adc, n * oh * ow,
                preferred_element_type=x.dtype)
        else:
            from ..fault.hw_aware import tiled_crossbar_matmul
            y = tiled_crossbar_matmul(
                self._patch_rows(x), wv, bk, bn, adc,
                preferred_element_type=x.dtype)
        return y.reshape(n, oh, ow, -1).transpose(0, 3, 1, 2)

    def apply(self, params, bottoms, ctx):
        # Shared filters applied to each bottom independently
        # (conv_layer.cpp loops over bottom.size()).
        tl = getattr(ctx, "tiles", None)
        tl = tl.get(self.name) if tl else None
        cb = getattr(ctx, "crossbar", None)
        cb = cb.get(self.name) if cb else None
        tops = []
        for x in bottoms:
            y = (self._crossbar_conv(x, params[0], ctx, tl, cb)
                 if tl is not None else self._conv(x, params[0]))
            if self.bias_term:
                y = y + params[1].reshape((1, -1) + (1,) * (y.ndim - 2))
            tops.append(y)
        return tops, None


@register_layer("Deconvolution")
class DeconvolutionLayer(_BaseConv):
    """reference deconv_layer.cpp: conv with forward/backward swapped ->
    lax.conv_transpose-equivalent via lhs dilation."""

    deconv = True

    def apply(self, params, bottoms, ctx):
        tl = getattr(ctx, "tiles", None)
        if tl and self.name in tl:
            # solver._check_tile_coverage refuses this earlier; the
            # guard here keeps a hand-built LayerContext loud too
            raise ValueError(
                f"layer {self.name!r}: Deconvolution has no im2col "
                "crossbar mapping (its GEMM transposes the weight "
                "view); train it untiled (tile_spec='1x1')")
        x = bottoms[0]
        # Gradient-of-conv formulation: dilate the input by stride, pad by
        # (effective_kernel - 1 - pad), and convolve with the flipped kernel.
        kh = [self.dilation[i] * (self.kernel[i] - 1) + 1
              for i in range(len(self.kernel))]
        padding = [(kh[i] - 1 - self.pad[i], kh[i] - 1 - self.pad[i])
                   for i in range(len(self.kernel))]
        # weight (I, O/g, kh, kw) -> flip spatial, swap to (O, I/g, kh, kw)
        w = params[0][:, :, ::-1, ::-1]
        i, og = w.shape[:2]
        w = w.reshape(self.group, i // self.group, og, *w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(og * self.group, i // self.group,
                                          *w.shape[3:])
        conv = functools.partial(
            lax.conv_general_dilated,
            window_strides=(1,) * len(self.stride),
            padding=padding,
            lhs_dilation=self.stride,
            rhs_dilation=self.dilation,
            dimension_numbers=DIMNUMS_2D,
            preferred_element_type=x.dtype)
        y = _grouped_conv(conv, x, w, self.group)
        if self.bias_term:
            y = y + params[1].reshape((1, -1) + (1,) * (y.ndim - 2))
        return [y], None


@register_layer("Pooling")
class PoolingLayer(Layer):
    """MAX/AVE/STOCHASTIC pooling with Caffe's CEIL output semantics
    (reference pooling_layer.cpp:85-96,165-256)."""

    def setup(self, bottom_shapes):
        pp = self.lp.pooling_param
        self.method = pp.pool
        kernel, self.stride, self.pad = pool_spatial_params(pp)
        n, c, h, w = bottom_shapes[0]
        if pp.global_pooling:
            kernel = (h, w)
            self.pad = (0, 0)
            self.stride = (1, 1)
        self.kernel = kernel
        ph = pooled_size(h, kernel[0], self.stride[0], self.pad[0])
        pw = pooled_size(w, kernel[1], self.stride[1], self.pad[1])
        self.in_hw = (h, w)
        self.out_hw = (ph, pw)
        # Explicit (lo, hi) padding reproducing ceil semantics under XLA's
        # floor-based window placement.
        self.xla_pad = (
            (self.pad[0], ceil_pad_hi(h, kernel[0], self.stride[0], self.pad[0], ph)),
            (self.pad[1], ceil_pad_hi(w, kernel[1], self.stride[1], self.pad[1], pw)),
        )
        self.top_shapes = [(n, c, ph, pw)]
        if len(self.lp.top) > 1:  # optional mask top (MAX only)
            self.top_shapes.append((n, c, ph, pw))
        return self.top_shapes

    def _reduce(self, x, init, op):
        return lax.reduce_window(
            x, init, op,
            window_dimensions=(1, 1) + self.kernel,
            window_strides=(1, 1) + self.stride,
            padding=((0, 0), (0, 0)) + self.xla_pad)

    def _patches(self, a, pad_value):
        """Extract pooling windows -> (N, C, kh*kw, PH, PW)."""
        (pl0, ph0), (pl1, ph1) = self.xla_pad
        apad = jnp.pad(a, ((0, 0), (0, 0), (pl0, ph0), (pl1, ph1)),
                       constant_values=pad_value)
        # HIGHEST: the one-hot extraction conv must reproduce values
        # bit-exactly (the mask path matches on equality; stochastic
        # pooling emits these values) — TPU's default MXU precision
        # rounds f32 operands through bf16
        p = lax.conv_general_dilated_patches(
            apad, filter_shape=self.kernel, window_strides=self.stride,
            padding=[(0, 0), (0, 0)], dimension_numbers=DIMNUMS_2D,
            precision=lax.Precision.HIGHEST)
        n_, _, oh, ow = p.shape
        return p.reshape(n_, a.shape[1], self.kernel[0] * self.kernel[1],
                         oh, ow)

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        if self.method == pb.PoolingParameter.MAX:
            # custom_vjp backward with a selectable engine (XLA
            # select_and_scatter by default — measured at the bandwidth
            # floor; the Pallas kernel alternative via RRAM_POOL_BWD) —
            # see ops/pool_backward.py
            from .pool_backward import max_pool
            y = max_pool(x, self.kernel, self.stride,
                         self.xla_pad).astype(x.dtype)
            tops = [y]
            if len(self.top_shapes) > 1:
                # Mask top: flat argmax index within the input feature map
                # (pooling_layer.cpp:147 emits a mask when a 2nd top exists).
                h, w = self.in_hw
                idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
                idx = jnp.broadcast_to(idx, x.shape)
                # finite pad: patches extract via a 0/1 conv, and
                # -inf * 0 = NaN would poison every window touching the
                # CEIL/pad fringe (equality match below never fires);
                # `ip >= 0` keeps a data value equal to finfo.min from
                # matching a pad slot. Mask stays f32: indices above the
                # mantissa range would round under bf16/f16 activations.
                xp = self._patches(x, jnp.finfo(x.dtype).min)
                ip = self._patches(idx, -1.0)
                sel = jnp.argmax((xp == y[:, :, None]) & (ip >= 0), axis=2)
                mask = jnp.take_along_axis(
                    ip, sel[:, :, None], axis=2).squeeze(2)
                tops.append(mask)
            return tops, None
        elif self.method == pb.PoolingParameter.AVE:
            s = self._reduce(x, 0.0, lax.add)
            h, w = self.in_hw
            dh = ave_pool_divisors(h, self.kernel[0], self.stride[0],
                                   self.pad[0], self.out_hw[0])
            dw = ave_pool_divisors(w, self.kernel[1], self.stride[1],
                                   self.pad[1], self.out_hw[1])
            div = jnp.asarray(np.outer(dh, dw), dtype=x.dtype)
            return [s / div], None
        else:  # STOCHASTIC (pooling_layer.cu: train samples ∝ value,
            #  test takes the value-weighted average)
            x_pos = jnp.maximum(x, 0.0)
            if self.phase == pb.TRAIN and ctx.rng is not None:
                xp = self._patches(x_pos, 0.0)
                cums = jnp.cumsum(xp, axis=2)
                total = cums[:, :, -1:]
                key = jax.random.fold_in(
                    ctx.rng, zlib.crc32(self.name.encode()) & 0x7FFFFFFF)
                r = jax.random.uniform(key, total.shape, dtype=x.dtype) * total
                sel = jnp.argmax(cums >= r, axis=2)
                y = jnp.take_along_axis(xp, sel[:, :, None], axis=2).squeeze(2)
            else:
                num = self._reduce(x_pos * x_pos, 0.0, lax.add)
                den = self._reduce(x_pos, 0.0, lax.add)
                y = jnp.where(den > 0, num / jnp.maximum(den, 1e-12), 0.0)
            return [y.astype(x.dtype)], None


@register_layer("LRN")
class LRNLayer(Layer):
    """Local response normalization, ACROSS_CHANNELS / WITHIN_CHANNEL
    (reference lrn_layer.cpp:118-164)."""

    def setup(self, bottom_shapes):
        lp = self.lp.lrn_param
        self.size = lp.local_size
        assert self.size % 2 == 1, "LRN local_size must be odd"
        self.alpha, self.beta, self.k = lp.alpha, lp.beta, lp.k
        self.across = (lp.norm_region == pb.LRNParameter.ACROSS_CHANNELS)
        self.top_shapes = [tuple(bottom_shapes[0])]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        sq = x * x
        half = (self.size - 1) // 2
        if self.across:
            # Channel-axis sliding sum as a sum of `size` shifted slices:
            # channel-dim reduce_window mis-lowers on the TPU AOT compiler,
            # and for the small window sizes LRN uses this fuses better.
            c = x.shape[1]
            padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
            ssum = padded[:, 0:c]
            for d in range(1, self.size):
                ssum = ssum + padded[:, d:d + c]
            scale = self.k + (self.alpha / self.size) * ssum
        else:
            ssum = lax.reduce_window(
                sq, 0.0, lax.add,
                window_dimensions=(1, 1, self.size, self.size),
                window_strides=(1, 1, 1, 1),
                padding=((0, 0), (0, 0), (half, half), (half, half)))
            scale = self.k + (self.alpha / (self.size * self.size)) * ssum
        return [x * lax.pow(scale, jnp.asarray(-self.beta, scale.dtype))], None


@register_layer("BatchNorm")
class BatchNormLayer(Layer):
    """Caffe-style BatchNorm: 3 state blobs {mean, variance, scale_factor},
    no learned affine (pair with Scale for that). Reference
    batch_norm_layer.cpp:14-140. Stats are updated functionally: apply
    returns replacement blob values instead of mutating.
    """

    def setup(self, bottom_shapes):
        bp = self.lp.batch_norm_param
        self.channels = bottom_shapes[0][1] if len(bottom_shapes[0]) > 1 else 1
        if bp.HasField("use_global_stats"):
            self.use_global_stats = bp.use_global_stats
        else:
            self.use_global_stats = (self.phase == pb.TEST)
        self.maf = bp.moving_average_fraction
        self.eps = bp.eps
        self.top_shapes = [tuple(bottom_shapes[0])]
        return self.top_shapes

    def num_params(self):
        return 3

    def param_specs(self):
        # BN statistics never receive solver updates
        # (batch_norm_layer.cpp:39 forces lr_mult 0).
        specs = super().param_specs()
        for s in specs:
            s.lr_mult = 0.0
            s.decay_mult = 0.0
        return specs

    def init_params(self, key):
        c = self.channels
        # explicit f32: default dtype would be f64 under x64 (the test
        # matrix), and f64 stats poison downstream conv dtypes
        return [jnp.zeros((c,), jnp.float32), jnp.zeros((c,), jnp.float32),
                jnp.zeros((1,), jnp.float32)]

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        mean_b, var_b, sf = params
        bshape = (1, -1) + (1,) * (x.ndim - 2)
        if self.use_global_stats:
            scale = jnp.where(sf[0] == 0, 0.0, 1.0 / jnp.maximum(sf[0], 1e-30))
            mean = mean_b * scale
            var = var_b * scale
            y = (x - mean.reshape(bshape)) * lax.rsqrt(
                var.reshape(bshape) + self.eps)
            return [y], None
        axes = (0,) + tuple(range(2, x.ndim))
        m = x.shape[0] * int(np.prod(x.shape[2:]))
        mean = jnp.mean(x, axis=axes)
        var = jnp.mean(jnp.square(x - mean.reshape(bshape)), axis=axes)
        y = (x - mean.reshape(bshape)) * lax.rsqrt(var.reshape(bshape) + self.eps)
        # Moving-average update (batch_norm_layer.cpp:120-130): the stored
        # stats are sums discounted by scale_factor. Accumulate in >=f32:
        # under a bf16 compute_dtype the steady-state increment (~1e-3 of
        # the stat) is below bf16's half-ulp and the average would freeze.
        acc = jnp.promote_types(x.dtype, jnp.float32)
        bias_corr = m / (m - 1.0) if m > 1 else 1.0
        new_mean = (self.maf * mean_b.astype(acc)
                    + lax.stop_gradient(mean).astype(acc))
        new_var = (self.maf * var_b.astype(acc)
                   + bias_corr * lax.stop_gradient(var).astype(acc))
        new_sf = self.maf * sf.astype(acc) + 1.0
        return [y], [new_mean, new_var, new_sf]


@register_layer("MVN")
class MVNLayer(Layer):
    """Mean-variance normalization (reference mvn_layer.cpp)."""

    def setup(self, bottom_shapes):
        mp = self.lp.mvn_param
        self.normalize_variance = mp.normalize_variance
        self.across_channels = mp.across_channels
        self.eps = mp.eps
        self.top_shapes = [tuple(bottom_shapes[0])]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        axes = tuple(range(1, x.ndim)) if self.across_channels \
            else tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        y = x - mean
        if self.normalize_variance:
            var = jnp.mean(jnp.square(y), axis=axes, keepdims=True)
            y = y / (jnp.sqrt(var) + self.eps)
        return [y], None


@register_layer("Crop")
class CropLayer(Layer):
    """Crop bottom[0] to bottom[1]'s shape from `axis` on, at `offset`
    (reference crop_layer.cpp)."""

    def setup(self, bottom_shapes):
        cp = self.lp.crop_param
        a, b = bottom_shapes[0], bottom_shapes[1]
        axis = cp.axis % len(a)
        offsets = list(cp.offset)
        self.starts = []
        out = list(a)
        for i in range(len(a)):
            off = 0
            if i >= axis:
                j = i - axis
                off = (offsets[j] if j < len(offsets)
                       else (offsets[0] if len(offsets) == 1 else 0))
                out[i] = b[i]
                assert off + b[i] <= a[i], \
                    f"crop exceeds bounds on axis {i}"
            self.starts.append(off)
        self.out_shape = tuple(out)
        self.top_shapes = [self.out_shape]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        return [lax.dynamic_slice(x, self.starts, self.out_shape)], None


@register_layer("Im2col")
class Im2colLayer(Layer):
    """Explicit im2col as a layer (reference im2col_layer.cpp). On TPU this
    exists only for parity/testing; real convs never materialize columns."""

    def setup(self, bottom_shapes):
        cp = self.lp.convolution_param
        self.kernel, self.stride, self.pad, self.dilation = conv_spatial_params(cp)
        n, c, h, w = bottom_shapes[0]
        oh = (h + 2 * self.pad[0]
              - (self.dilation[0] * (self.kernel[0] - 1) + 1)) // self.stride[0] + 1
        ow = (w + 2 * self.pad[1]
              - (self.dilation[1] * (self.kernel[1] - 1) + 1)) // self.stride[1] + 1
        self.top_shapes = [(n, c * self.kernel[0] * self.kernel[1], oh, ow)]
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        x = bottoms[0]
        patches = lax.conv_general_dilated_patches(
            x, filter_shape=self.kernel, window_strides=self.stride,
            padding=[(p, p) for p in self.pad], rhs_dilation=self.dilation,
            dimension_numbers=DIMNUMS_2D)
        return [patches.reshape(self.top_shapes[0])], None

"""Pure-JAX layer implementations (reference: src/caffe/layers/*).

Importing this package registers every layer type with the LAYER_REGISTRY.
"""
from . import data_layers  # noqa: F401
from . import vision  # noqa: F401
from . import common  # noqa: F401
from . import neuron  # noqa: F401
from . import losses  # noqa: F401
from . import recurrent  # noqa: F401
from . import extra  # noqa: F401
from . import attention  # noqa: F401

"""Max-pool backward as a fused Pallas TPU kernel.

XLA derives max-pool's VJP as `select_and_scatter` — on the Monte-Carlo
sweep it is the largest single op and HBM-bound (round-2 profile): the
scatter re-reads the forward input and output and walks windows with
poor locality. This kernel computes the SAME quantity in one pass:
x and the cotangent g stream HBM->VMEM once per block, the per-window
first-argmax selection and the scatter both happen entirely in VMEM
(the k^2-wide patch tensor that OOMs in HBM at sweep shapes is a few
hundred KB per block there), and dx streams out once. Replaces the
capability of the reference's hand-written pooling backward kernel
(`src/caffe/layers/pooling_layer.cu` MaxPoolBackward).

Tie semantics match XLA/Caffe exactly: the FIRST element (row-major
window order) attaining the window max receives the gradient
(`jnp.argmax` first-occurrence == SelectAndScatter's GE select ==
MaxPoolForward's `>` update rule). One documented divergence: the
kernel pads with float32 finfo.min rather than -inf, so an input
window whose REAL values are all -inf would route its cotangent to the
padding (dropped) where XLA ties pad -inf against value -inf — only
reachable with -inf activations, which no finite net produces.

`max_pool(x, ...)` is a drop-in for the reduce_window forward with a
`custom_vjp`: backward goes through the Pallas kernel on the TPU
backend (or interpret mode under tests) and falls back to XLA's own
VJP elsewhere — numerics are pinned equal in tests/test_pool_backward.py.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax


def _fwd_reduce(x, kernel, stride, xla_pad):
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1) + tuple(kernel),
        window_strides=(1, 1) + tuple(stride),
        padding=((0, 0), (0, 0)) + tuple(xla_pad))


def _bwd_kernel(g_ref, x_ref, dx_ref, xp_ref, *, hw, ohw, kernel,
                stride, pads):
    """Mosaic-friendly body: no reshapes, no strided slices. Window
    maxima and the first-argmax offset are computed at FULL anchor
    resolution with stride-1 shifted slices; the stride decimation /
    dilation between anchor and window grids is expressed as two tiny
    0/1 selection-matrix matmuls (MXU work, no vector shuffles)."""
    H, W = hw
    Ho, Wo = ohw
    kh, kw = kernel
    sh, sw = stride
    (pl0, phi0), (pl1, phi1) = pads
    # anchor grid must reach anchor (Ho-1)*sh + window extent kh
    Hp = max(H + pl0 + phi0, (Ho - 1) * sh + kh)
    Wp = max(W + pl1 + phi1, (Wo - 1) * sw + kw)
    out_dtype = x_ref.dtype
    # all selection math in f32 (exact upcast): sub-f32 dtypes trip
    # Mosaic's comparison layouts, and VMEM-resident upcasts are free
    # next to the HBM streams
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    rb = x.shape[0]
    neg = jnp.finfo(jnp.float32).min         # pad loses to any real value
    # ONE fixed (rb, Hp, Wp) frame for everything: Mosaic rejects
    # strided slices, pad/concatenate, and dynamic_update_slice, so the
    # frame is a VMEM scratch written through sliced ref stores; windows
    # are read through rolls (wrap regions land on anchors the selection
    # matrices zero out), and the anchor<->window stride mapping is two
    # 0/1 matmuls.
    xp_ref[...] = jnp.full((rb, Hp, Wp), neg, x.dtype)
    xp_ref[:, pl0:pl0 + H, pl1:pl1 + W] = x
    xp = xp_ref[...]

    def shifted(ki, kj):                     # value at anchor + offset
        out = xp
        if ki:                               # roll-by-0 makes Mosaic
            out = jnp.roll(out, -ki, axis=1)  # emit zero-size slices
        if kj:
            out = jnp.roll(out, -kj, axis=2)
        return out

    k2 = kh * kw
    wmax = shifted(0, 0)
    for lin in range(1, k2):
        wmax = jnp.maximum(wmax, shifted(lin // kw, lin % kw))
    first = jnp.full((rb, Hp, Wp), k2, jnp.int32)
    for lin in range(k2):                    # row-major: first max wins
        eq = shifted(lin // kw, lin % kw) == wmax
        first = jnp.where(eq & (first == k2), lin, first)

    # g upsampled onto the frame's anchor positions:
    # U_h[a, oh] = [a == pl-less anchor oh*sh], zero at every invalid
    # or roll-wrapped anchor
    f32 = jnp.float32
    u_h = (lax.broadcasted_iota(jnp.int32, (Hp, Ho), 0) ==
           lax.broadcasted_iota(jnp.int32, (Hp, Ho), 1) * sh) \
        .astype(f32)
    u_w = (lax.broadcasted_iota(jnp.int32, (Wp, Wo), 0) ==
           lax.broadcasted_iota(jnp.int32, (Wp, Wo), 1) * sw) \
        .astype(f32)
    # HIGHEST precision: the default MXU path rounds f32 operands
    # through bf16, corrupting the cotangent VALUES (selection itself is
    # exact); with 0/1 selectors the 3-pass f32 product is exact
    gu = jnp.einsum("ah,rhw->raw", u_h, g,
                    precision=lax.Precision.HIGHEST)
    gu = jnp.einsum("raw,bw->rab", gu, u_w,
                    precision=lax.Precision.HIGHEST)  # (rb, Hp, Wp)

    acc = jnp.zeros((rb, Hp, Wp), f32)
    for lin in range(k2):
        ki, kj = lin // kw, lin % kw
        t = jnp.where(first == lin, gu, 0.0)
        # place at (anchor + offset): nonzero rows sit at anchors
        # <= (Ho-1)*sh <= Hp-kh, so rolling by ki < kh wraps only zeros
        if ki:
            t = jnp.roll(t, ki, axis=1)
        if kj:
            t = jnp.roll(t, kj, axis=2)
        acc = acc + t
    dx_ref[...] = lax.slice(
        acc, (0, pl0, pl1), (rb, pl0 + H, pl1 + W)).astype(out_dtype)


def _pick_rb(r: int, cap: int = 8) -> int:
    """Largest divisor of r up to `cap` rows per block (Mosaic compile
    time and VMEM pressure grow with the unrolled block row count —
    every (rb, H, ~W) temporary lane-pads W up to 128; 8 rows keeps the
    ~20 live unrolled temporaries inside the 16 MB scoped VMEM limit)."""
    best = 1
    for rb in range(1, min(r, cap) + 1):
        if r % rb == 0:
            best = rb
    return best


def _pallas_bwd(g, x, kernel, stride, pads, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    lead = x.shape[:-2]
    H, W = x.shape[-2:]
    Ho, Wo = g.shape[-2:]
    r = 1
    for d in lead:
        r *= d
    rb = _pick_rb(r)
    kern = functools.partial(
        _bwd_kernel, hw=(H, W), ohw=(Ho, Wo),
        kernel=tuple(kernel), stride=tuple(stride), pads=tuple(pads))
    kh, kw = kernel
    (pl0, phi0), (pl1, phi1) = pads
    hp = max(H + pl0 + phi0, (Ho - 1) * stride[0] + kh)
    wp = max(W + pl1 + phi1, (Wo - 1) * stride[1] + kw)
    out = pl.pallas_call(
        kern,
        grid=(r // rb,),
        in_specs=[pl.BlockSpec((rb, Ho, Wo), lambda i: (i, 0, 0)),
                  pl.BlockSpec((rb, H, W), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((rb, H, W), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((r, H, W), x.dtype),
        scratch_shapes=[pltpu.VMEM((rb, hp, wp), jnp.float32)],
        interpret=interpret,
    )(g.reshape((r, Ho, Wo)), x.reshape((r, H, W)))
    return out.reshape(x.shape)


def _engine() -> str:
    """auto (== xla) | pallas | xla | interpret — RRAM_POOL_BWD
    overrides.

    MEASURED OUTCOME (round 3, v5e): XLA's select_and_scatter is NOT
    the lever the round-2 profile hypothesized. Head-to-head at
    representative sweep shapes (dispatch-amortized fori loops), the
    Pallas kernel runs ~2.3x slower (9.7 vs 4.3 ms at 8192 planes of
    32x32/f32 and bf16 alike): with W=32 feature maps every VMEM
    temporary lane-pads to 128 (4x wasted vector bandwidth), while
    XLA's native scatter streams the op at its layout of choice. At
    full 256-config sweep scale the custom-call boundary additionally
    materializes re-layout copies that push the step over the 15.75 GB
    HBM budget. The kernel therefore stays an exactness-pinned
    ALTERNATIVE engine (tie semantics and values equal to XLA,
    tests/test_pool_backward.py) rather than the default — the honest
    roofline conclusion is that the sweep step was already at its
    bandwidth floor.
    """
    return os.environ.get("RRAM_POOL_BWD", "auto")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def max_pool(x, kernel, stride, xla_pad):
    """reduce_window max forward with the Pallas backward (see module
    docstring). kernel/stride/xla_pad are the spatial (h, w) params with
    Caffe CEIL padding already folded into xla_pad."""
    return _fwd_reduce(x, kernel, stride, xla_pad)


def _max_pool_fwd(x, kernel, stride, xla_pad):
    return _fwd_reduce(x, kernel, stride, xla_pad), x


def _max_pool_bwd(kernel, stride, xla_pad, x, g):
    eng = _engine()
    if eng == "auto":
        eng = "xla"          # measured faster at sweep shapes; see above
    if eng in ("pallas", "interpret"):
        dx = _pallas_bwd(g, x, kernel, stride, xla_pad,
                         interpret=(eng == "interpret"))
    else:
        _, vjp = jax.vjp(
            lambda a: _fwd_reduce(a, kernel, stride, xla_pad), x)
        dx, = vjp(g)
    return (dx,)


max_pool.defvjp(_max_pool_fwd, _max_pool_bwd)

"""Recurrent layers (reference: src/caffe/layers/{recurrent,rnn,lstm,
lstm_unit}_layer.cpp).

The reference unrolls T timesteps into an internal Net
(recurrent_layer.hpp:151 unrolled_net_, subclass hook FillUnrolledNet);
here the unroll is a `lax.scan`, which XLA compiles to a rolled loop — same
math, no T-times graph duplication, differentiable through time
automatically.

Semantics preserved exactly:
- bottoms: x (T,N,...), cont (T,N) sequence-continuation indicator
  (recurrent_layer.cpp:34: cont_t = 0 at sequence starts), optional
  x_static (N,...), optional initial recurrent state(s) when
  expose_hidden (recurrent_layer.hpp:41).
- RNN (rnn_layer.cpp:98-227): h_t = tanh(W_hh (cont_t * h_{t-1}) +
  W_xh x_t + b_h [+ W_xh_static x_static]); o_t = tanh(W_ho h_t + b_o).
  Param blob order [W_xh, b_h, (W_xh_static), W_hh, W_ho, b_o] follows the
  unrolled net's creation order, so .caffemodel weights load unchanged.
- LSTM (lstm_layer.cpp:107-244, lstm_unit_layer.cpp:41-66): gate_input =
  W_hc (cont_t*h_{t-1}) + W_xc x_t + b_c [+ W_xc_static x_static], gates
  ordered [i, f, o, g]; c_t = cont_t*f*c_{t-1} + i*g; h_t = o*tanh(c_t).
  Params [W_xc, b_c, (W_xc_static), W_hc].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.fillers import make_filler
from ..core.registry import Layer, register_layer


class RecurrentLayer(Layer):
    """Base: bottom/top bookkeeping shared by RNN and LSTM
    (recurrent_layer.cpp:20-136)."""

    # subclass contract
    n_recur_blobs = 1          # h only (LSTM: c and h)

    def setup(self, bottom_shapes):
        rp = self.lp.recurrent_param
        self.D = int(rp.num_output)
        assert self.D > 0, "num_output must be positive"
        self.expose_hidden = rp.expose_hidden
        x_shape = bottom_shapes[0]
        self.T, self.N = int(x_shape[0]), int(x_shape[1])
        self.I = 1
        for d in x_shape[2:]:
            self.I *= int(d)
        n_hidden_exposed = (self.n_recur_blobs if self.expose_hidden else 0)
        self.static_input = len(bottom_shapes) > 2 + n_hidden_exposed
        if self.static_input:
            self.S = 1
            for d in bottom_shapes[2][1:]:
                self.S *= int(d)
        tops = [(self.T, self.N, self.D)]
        if self.expose_hidden:
            tops += [(1, self.N, self.D)] * self.n_recur_blobs
        self.top_shapes = tops
        return tops

    def _fillers(self):
        rp = self.lp.recurrent_param
        return make_filler(rp.weight_filler), make_filler(rp.bias_filler)


@register_layer("RNN")
class RNNLayer(RecurrentLayer):
    n_recur_blobs = 1

    def num_params(self):
        return 6 if self.static_input else 5

    def init_params(self, key):
        wf, bf = self._fillers()
        keys = jax.random.split(key, 4)
        params = [wf(keys[0], (self.D, self.I)),      # W_xh
                  bf(keys[1], (self.D,))]             # b_h
        if self.static_input:
            key_s = jax.random.fold_in(key, 99)
            params.append(wf(key_s, (self.D, self.S)))  # W_xh_static
        params += [wf(keys[2], (self.D, self.D)),     # W_hh
                   wf(keys[3], (self.D, self.D))]     # W_ho
        params.append(bf(jax.random.fold_in(key, 100), (self.D,)))  # b_o
        return params

    def apply(self, params, bottoms, ctx):
        x, cont = bottoms[0], bottoms[1]
        i = 2
        x_static = None
        if self.static_input:
            x_static = bottoms[i]
            i += 1
        T_, N_ = x.shape[0], x.shape[1]
        if self.expose_hidden and len(bottoms) > i:
            h0 = bottoms[i].reshape(N_, self.D)
        else:
            h0 = jnp.zeros((N_, self.D), x.dtype)
        if self.static_input:
            W_xh, b_h, W_xs, W_hh, W_ho, b_o = params
            static_term = x_static.reshape(N_, self.S) @ W_xs.T
        else:
            W_xh, b_h, W_hh, W_ho, b_o = params
            static_term = 0.0
        xt = x.reshape(T_, N_, self.I) @ W_xh.T + b_h

        def step(h_prev, inp):
            x_t, cont_t = inp
            h_conted = h_prev * cont_t[:, None]
            h = jnp.tanh(h_conted @ W_hh.T + x_t + static_term)
            o = jnp.tanh(h @ W_ho.T + b_o)
            return h, o

        h_final, o_seq = jax.lax.scan(step, h0, (xt, cont.astype(x.dtype)))
        tops = [o_seq]
        if self.expose_hidden:
            tops.append(h_final[None])
        return tops, None


@register_layer("LSTM")
class LSTMLayer(RecurrentLayer):
    n_recur_blobs = 2   # c and h (recur order: c_0, h_0 — lstm_layer.cpp:41)

    def num_params(self):
        return 4 if self.static_input else 3

    def init_params(self, key):
        wf, bf = self._fillers()
        keys = jax.random.split(key, 3)
        params = [wf(keys[0], (4 * self.D, self.I)),   # W_xc
                  bf(keys[1], (4 * self.D,))]          # b_c
        if self.static_input:
            params.append(wf(jax.random.fold_in(key, 99),
                             (4 * self.D, self.S)))    # W_xc_static
        params.append(wf(keys[2], (4 * self.D, self.D)))  # W_hc
        return params

    def apply(self, params, bottoms, ctx):
        x, cont = bottoms[0], bottoms[1]
        i = 2
        x_static = None
        if self.static_input:
            x_static = bottoms[i]
            i += 1
        T_, N_ = x.shape[0], x.shape[1]
        if self.expose_hidden and len(bottoms) > i + 1:
            c0 = bottoms[i].reshape(N_, self.D)
            h0 = bottoms[i + 1].reshape(N_, self.D)
        else:
            c0 = jnp.zeros((N_, self.D), x.dtype)
            h0 = jnp.zeros((N_, self.D), x.dtype)
        if self.static_input:
            W_xc, b_c, W_xs, W_hc = params
            static_term = x_static.reshape(N_, self.S) @ W_xs.T
        else:
            W_xc, b_c, W_hc = params
            static_term = 0.0
        xt = x.reshape(T_, N_, self.I) @ W_xc.T + b_c

        D = self.D

        def step(carry, inp):
            c_prev, h_prev = carry
            x_t, cont_t = inp
            h_conted = h_prev * cont_t[:, None]
            gates = h_conted @ W_hc.T + x_t + static_term
            c, h = _lstm_unit(c_prev, gates, cont_t, D)
            return (c, h), h

        (c_final, h_final), h_seq = jax.lax.scan(
            step, (c0, h0), (xt, cont.astype(x.dtype)))
        tops = [h_seq]
        if self.expose_hidden:
            tops += [c_final[None], h_final[None]]
        return tops, None


def _lstm_unit(c_prev, gates, cont_t, D):
    """LSTMUnit math (lstm_unit_layer.cpp:41-66), gate order [i, f, o, g];
    f is cont-scaled so c_prev is forgotten at sequence starts."""
    i = jax.nn.sigmoid(gates[:, 0 * D:1 * D])
    f = cont_t[:, None] * jax.nn.sigmoid(gates[:, 1 * D:2 * D])
    o = jax.nn.sigmoid(gates[:, 2 * D:3 * D])
    g = jnp.tanh(gates[:, 3 * D:4 * D])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return c, h


@register_layer("LSTMUnit")
class LSTMUnitLayer(Layer):
    """Standalone single-step LSTM unit (lstm_unit_layer.cpp): bottoms
    c_prev (1,N,D), gate_input (1,N,4D), cont (1,N); tops c, h."""

    def setup(self, bottom_shapes):
        self.D = int(bottom_shapes[0][2])
        self.top_shapes = [tuple(bottom_shapes[0])] * 2
        return self.top_shapes

    def apply(self, params, bottoms, ctx):
        c_prev, gates, cont = bottoms
        n = c_prev.shape[1]
        c, h = _lstm_unit(c_prev.reshape(n, self.D),
                          gates.reshape(n, 4 * self.D),
                          cont.reshape(n), self.D)
        return [c[None], h[None]], None

"""Shared shape/parameter helpers for layer implementations."""
from __future__ import annotations

import math

import numpy as np


def conv_spatial_params(cp, num_spatial: int = 2):
    """Resolve kernel/stride/pad/dilation from a ConvolutionParameter.

    Mirrors BaseConvolutionLayer::LayerSetUp's handling of repeated fields vs
    the 2-D *_h/*_w overrides (reference base_conv_layer.cpp:17-110).
    """
    if cp.HasField("kernel_h") or cp.HasField("kernel_w"):
        kernel = (cp.kernel_h, cp.kernel_w)
    else:
        ks = list(cp.kernel_size)
        assert ks, "kernel_size required"
        kernel = tuple(ks[i] if len(ks) > 1 else ks[0] for i in range(num_spatial))
    if cp.HasField("stride_h") or cp.HasField("stride_w"):
        stride = (cp.stride_h, cp.stride_w)
    else:
        ss = list(cp.stride) or [1]
        stride = tuple(ss[i] if len(ss) > 1 else ss[0] for i in range(num_spatial))
    if cp.HasField("pad_h") or cp.HasField("pad_w"):
        pad = (cp.pad_h, cp.pad_w)
    else:
        ps = list(cp.pad) or [0]
        pad = tuple(ps[i] if len(ps) > 1 else ps[0] for i in range(num_spatial))
    ds = list(cp.dilation) or [1]
    dilation = tuple(ds[i] if len(ds) > 1 else ds[0] for i in range(num_spatial))
    return kernel, stride, pad, dilation


def pool_spatial_params(pp):
    """Resolve kernel/stride/pad for PoolingParameter (2-D only), honoring
    global_pooling (reference pooling_layer.cpp:38-90)."""
    if pp.HasField("kernel_h") or pp.HasField("kernel_w"):
        kernel = (pp.kernel_h, pp.kernel_w)
    elif pp.HasField("kernel_size"):
        kernel = (pp.kernel_size, pp.kernel_size)
    else:
        kernel = None  # global pooling fills this in from the bottom shape
    if pp.HasField("stride_h") or pp.HasField("stride_w"):
        stride = (pp.stride_h, pp.stride_w)
    else:
        stride = (pp.stride, pp.stride)
    if pp.HasField("pad_h") or pp.HasField("pad_w"):
        pad = (pp.pad_h, pp.pad_w)
    else:
        pad = (pp.pad, pp.pad)
    return kernel, stride, pad


def pooled_size(h: int, k: int, s: int, p: int) -> int:
    """Caffe pooled output size: CEIL division, clipped so the last window
    starts inside the image (reference pooling_layer.cpp:85-96)."""
    out = int(math.ceil((h + 2 * p - k) / float(s))) + 1
    if p > 0 and (out - 1) * s >= h + p:
        out -= 1
    return out


def ceil_pad_hi(h: int, k: int, s: int, p: int, out: int) -> int:
    """Right/bottom padding so floor-semantics windows produce `out` outputs
    with `p` low padding."""
    return max(0, (out - 1) * s + k - h - p)


def ave_pool_divisors(h: int, k: int, s: int, p: int, out: int) -> np.ndarray:
    """Per-output-position divisor for AVE pooling along one axis.

    Caffe divides by the window's intersection with the padded extent
    [−p, h+p): hstart = o*s − p is NOT clipped low, hend is clipped to h+p
    (reference pooling_layer.cpp:172-180).
    """
    o = np.arange(out)
    hstart = o * s - p
    hend = np.minimum(hstart + k, h + p)
    return (hend - hstart).astype(np.float64)


def flat_shape_from(shape, axis: int) -> tuple[int, int]:
    """Collapse shape into (outer, inner) at `axis` (Caffe count(0,axis) x
    count(axis))."""
    axis = axis % len(shape) if axis < 0 else axis
    outer = int(np.prod(shape[:axis])) if axis > 0 else 1
    inner = int(np.prod(shape[axis:])) if axis < len(shape) else 1
    return outer, inner

"""FleetWorker — a pod-backed `SweepService` wrapped for the fleet.

One worker process = one warm `SweepService` lane pool (its own mesh
topology via ``--mesh``) living in ``<fleet>/workers/<name>/`` plus
the fleet chores around it:

- **registration + heartbeats** (table.py): the worker publishes its
  pinned program set — canonical (fault_process, dtype_policy, net,
  tiles, mesh) — and refreshes its row with live load every tick; a
  worker whose row the controller removed (declared dead after a
  stale heartbeat) stops serving instead of double-running requests
  that already requeued elsewhere;
- **hot program swap**: on a ``<name>.swap.json`` command the worker
  pauses admission (race-free: the service checks the command file at
  every admission pass), lets in-flight requests finish, then
  ACTIVATES the service for the new pins. The previous service is
  PARKED, not torn down — the resident program cache
  (``--resident-programs``) keeps its compiled executables and device
  state in memory, so swapping back to a set this worker held before
  is a pure re-activation: zero compiles, zero persistent-cache
  deserialization, swap = re-place state + program-cache hit. A
  first-seen set builds fresh (the decoded-dataset cache and any
  key-matching XLA entries from the ``--cache-dir`` snapshot soften
  it). The measured latency, `resident` flag, and cache counter
  delta land on a `worker` record (event "swap") plus a `span`
  record in the worker's metrics stream;
- **drain**: the controller's per-worker DRAIN file flows through the
  service's normal drain path (in-flight work checkpointed, exit 75;
  idle exit 0) and the worker unregisters its row — a clean departure
  (missing row), distinct from a death (stale row).

    python -m rram_caffe_simulation_tpu.serve.fleet.worker \\
        --fleet-dir /runs/fleet --name w0 \\
        --solver models/.../solver.prototxt --lanes 8 --chunk 8
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

from .table import WorkerTable

#: how many service scheduling beats run between fleet chores
#: (heartbeat + swap check) — at the service's poll interval this
#: bounds heartbeat staleness for an idle worker
DEFAULT_TICK_BEATS = 2


class FleetWorker:
    """One fleet worker: service + registration + swap machinery."""

    def __init__(self, fleet_dir: str, name: str, solver: str, *,
                 nets: Optional[Dict[str, str]] = None,
                 fault_process: Optional[str] = None,
                 tile_spec: Optional[str] = None,
                 dtype_policy: Optional[str] = None,
                 net_name: Optional[str] = None,
                 tick_beats: int = DEFAULT_TICK_BEATS,
                 resident_programs: int = 2,
                 service_kw: Optional[dict] = None):
        self.table = WorkerTable(fleet_dir)
        self.name = str(name)
        self.dir = self.table.worker_dir(self.name)
        #: net name -> solver prototxt path; swaps may only re-pin to
        #: nets this worker was launched knowing about
        self.nets = dict(nets or {})
        if net_name is None:
            net_name = os.path.splitext(os.path.basename(solver))[0]
        self.nets.setdefault(net_name, solver)
        self.tick_beats = max(int(tick_beats), 1)
        self.service_kw = dict(service_kw or {})
        #: the resident program cache: canonical-pinned-set -> PARKED
        #: SweepService, compiled executables and all. Swapping back
        #: to a resident set is a pure in-memory re-activation — zero
        #: compiles, zero persistent-cache deserialization — which is
        #: what makes the hot swap actually hot (and sidesteps a
        #: jaxlib fragility: deserializing cached AOT executables
        #: intermittently corrupts the heap on CPU jaxlib 0.4.36).
        #: Dormant services keep their device state resident; size the
        #: cache (`--resident-programs`) to the tenant shapes you
        #: oscillate between and the accelerator memory you can spare.
        self.resident_programs = max(int(resident_programs), 1)
        self._resident: Dict[str, object] = {}
        self.swap_count = 0
        self.service = None
        t0 = time.perf_counter()
        self._construct(net_name, fault_process, tile_spec,
                        dtype_policy)
        self._setup_s = time.perf_counter() - t0
        row = self._row_fields()
        row["setup_s"] = round(self._setup_s, 3)
        self.table.register(self.name, row)
        self.service._log_service_record(self._worker_record(
            "registered", pinned=self.service.pinned(),
            lanes=self.service.runner.n))

    # ------------------------------------------------------------------
    # service construction + the resident program cache

    @staticmethod
    def _pin_key(pinned: Dict[str, str]) -> str:
        return json.dumps({str(k): str(v) for k, v in pinned.items()},
                          sort_keys=True)

    def _sockets_enabled(self) -> bool:
        return self.service_kw.get("socket_path", "") is not None

    def _construct(self, net_name: str, fault_process, tile_spec,
                   dtype_policy):
        """Build a fresh SweepService for the pinned set, make it the
        active one, and register it in the resident cache."""
        from ..service import SweepService
        solver = self.nets.get(net_name)
        if solver is None:
            raise ValueError(
                f"worker {self.name} does not know net {net_name!r} "
                f"(launched with {sorted(self.nets)}) — pass it via "
                "--net NAME=SOLVER")
        if dtype_policy in (None, "f32"):
            dtype_policy = None
        svc = SweepService(
            solver, self.dir,
            fault_process=fault_process, tile_spec=tile_spec,
            dtype_policy=dtype_policy, net_name=net_name,
            **self.service_kw)
        # race-free swap ordering: the controller writes the swap
        # command STRICTLY BEFORE routing mismatched requests into
        # this spool, and the service checks this gate at every
        # admission pass — so a freshly routed request can never be
        # admitted (and pin-rejected) by the pre-swap program, however
        # the file writes interleave with the serve loop
        svc.admission_gate = (
            lambda: self.table.read_swap(self.name) is None)
        self.service = svc
        self._resident[self._pin_key(svc.pinned())] = svc
        self._evict_residents()
        return svc

    def _activate(self, target: Dict[str, str]) -> bool:
        """Make the service for `target` active: a resident
        re-activation when this worker held it before (True), a fresh
        construction otherwise (False). The previous service is
        PARKED, not closed — its compiled programs and device state
        stay resident for the swap back."""
        old = self.service
        old.suspend_socket()
        key = self._pin_key(target)
        cached = self._resident.pop(key, None)
        if cached is not None:
            self._resident[key] = cached      # LRU bump
            self.service = cached
            cached.pause_admission = False
            if self._sockets_enabled():
                cached.resume_socket()
            return True
        self._construct(target.get("net", old.net_name),
                        target.get("process"), target.get("tiles"),
                        target.get("dtype_policy"))
        return False

    def _return_mismatched_pending(self, target: Dict[str, str]):
        """Move still-pending worker-spool requests whose pins do not
        match the swap TARGET back to the fleet spool (at the fleet
        level they are `active`, claimed to us — requeue strips the
        claim so the controller re-routes them)."""
        from ..spool import Spool
        from .controller import canonicalize_pins
        from .router import request_pins
        fleet_spool = None
        for rid in self.service.spool.pending_ids():
            req = self.service.spool.read(rid)
            if req is None:
                continue
            try:
                pins = canonicalize_pins(request_pins(req))
            except ValueError:
                continue   # the post-swap admission will reject it
            if all(target.get(k) == v for k, v in pins.items()):
                continue
            if fleet_spool is None:
                fleet_spool = Spool(os.path.join(self.table.fleet_dir,
                                                 "spool"))
            try:
                fleet_spool.requeue(rid)
            except (OSError, ValueError):
                continue   # not fleet-claimed (direct submission)
            try:
                os.remove(self.service.spool._path("pending", rid))
            except OSError:
                pass
            print(f"Fleet worker {self.name}: returned pending "
                  f"request {rid} to the fleet spool (pins {pins} do "
                  "not match the swap target)", flush=True)

    def _evict_residents(self):
        while len(self._resident) > self.resident_programs:
            for key, svc in self._resident.items():
                if svc is not self.service:
                    del self._resident[key]
                    svc.close()
                    break
            else:
                return

    # ------------------------------------------------------------------
    # table plumbing

    def _row_fields(self) -> dict:
        import socket
        view = self.service.stats()
        reqs = view.get("requests") or {}
        occ = view.get("occupancy") or {}
        slo = (view.get("slo") or {}).get("_total") or {}
        stats = {
            "iter": int(view.get("iter") or 0),
            "requests": {str(k): int(v) for k, v in reqs.items()},
            "active_requests": int(reqs.get("running") or 0)
                               + int(reqs.get("admitted") or 0),
            "projected_s": round(float(view.get("projected_s")
                                       or 0.0), 3),
            "occupancy": round(float(occ.get("occupancy") or 0.0),
                               4),
            "slo_burn": round(float(slo.get("burn_rate") or 0.0),
                              4),
            "projection_bias": round(float(slo.get(
                "projection_bias") or 0.0), 4),
        }
        # crossbar health plane: the wear-ledger rollup rides the
        # heartbeat row only once censuses exist, so the controller
        # can tell "health off / no data yet" from "healthy"
        if isinstance(view.get("health"), dict):
            stats["health"] = view["health"]
        return {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "pinned": self.service.pinned(),
            "nets": sorted(self.nets),
            "lanes": int(view.get("lanes") or 0),
            "occupied_lanes": int(view.get("occupied_lanes") or 0),
            "pending_configs": int(view.get("pending_configs") or 0),
            "steps_per_sec": float(view.get("steps_per_sec") or 0.0),
            "swap_count": self.swap_count,
            # watchtower snapshot: enough state on the heartbeat row
            # for ServeClient stats and the controller's rollup to
            # work SOCKET-FREE from the worker table alone
            "stats": stats,
        }

    def _worker_record(self, event: str, **kw) -> dict:
        from ...observe import make_worker_record
        kw = {k: v for k, v in kw.items() if v is not None}
        return make_worker_record(int(self.service.runner.iter),
                                  self.name, event, **kw)

    def _heartbeat(self) -> bool:
        """Refresh the table row; False when the row is gone (the
        controller declared this worker dead — stop serving)."""
        return self.table.heartbeat(self.name,
                                    self._row_fields()) is not None

    # ------------------------------------------------------------------
    # hot swap

    def _maybe_swap(self) -> bool:
        """Apply a queued swap command once no request is in flight.
        Returns True when a swap was applied (the service object was
        replaced)."""
        cmd = self.table.read_swap(self.name)
        if cmd is None:
            return False
        target = {str(k): str(v)
                  for k, v in (cmd.get("pinned") or {}).items()}
        if target == self.service.pinned():
            self.table.clear_swap(self.name)
            return False
        # while the command stands, the admission gate holds pending
        # requests for the rebuilt service whose pins they match;
        # in-flight ones finish under the old program first
        if self.service._active_ids():
            return False
        net_name = target.get("net", self.service.net_name)
        if net_name not in self.nets:
            # refusal protocol: clear the command so the controller's
            # reconcile pass (swap file gone + row still un-re-pinned)
            # drops its pending_swap overlay instead of wedging
            self.table.clear_swap(self.name)
            self.service._log_service_record(self._worker_record(
                "swap_refused", pinned=target,
                reason=f"unknown net {net_name!r} (worker knows "
                       f"{sorted(self.nets)})"))
            return False
        # requests validly routed here BEFORE the swap command landed
        # (they match the CURRENT pins, not the target) go back to the
        # fleet spool for re-routing — the post-swap service would
        # pin-reject them terminally otherwise
        self._return_mismatched_pending(target)
        from ... import cache as perf_cache
        c0 = perf_cache.compile_cache_stats()
        wall0 = time.time()
        t0 = time.perf_counter()
        resident = self._activate(target)
        # publish the new pins BEFORE clearing the command: the
        # controller's reconcile pass distinguishes "applied" (row ==
        # target) from "refused" (row unchanged) once the swap file is
        # gone, so the row must never lag the clear
        self.table.heartbeat(self.name, self._row_fields())
        # consume the command BEFORE the warm beat (the gate opens),
        # then run one serving beat INSIDE the swap window: a fresh
        # program's XLA compiles are lazy (they fire at the first
        # dispatched chunk), so this is where "re-place state +
        # program-cache hit, not a cold start" is actually proven —
        # the beat admits the requests that were waiting for the new
        # pins and dispatches their first chunk (a RESIDENT
        # re-activation's dispatch reuses the in-memory compiled
        # executables: zero compiles of any kind)
        self.table.clear_swap(self.name)
        self.service.serve(max_beats=1)
        swap_s = time.perf_counter() - t0
        c1 = perf_cache.compile_cache_stats()
        self.swap_count += 1
        self.table.heartbeat(self.name, self._row_fields())
        rec = self._worker_record(
            "swap", pinned=self.service.pinned(), swap_s=swap_s,
            resident=resident,
            cache_hits=c1["hits"] - c0["hits"],
            cache_misses=c1["misses"] - c0["misses"])
        self.service._log_service_record(rec)
        # the swap latency as a span on the fleet timeline (ISSUE 15):
        # same record stream, Perfetto-ready shape
        from ...observe.schema import SCHEMA_VERSION
        self.service._log_service_record({
            "schema_version": SCHEMA_VERSION, "type": "span",
            "iter": int(self.service.runner.iter), "wall_time": wall0,
            "name": "swap", "cat": "fleet", "kind": "span",
            "dur_s": round(swap_s, 6), "thread": "fleet-worker",
            "process": 0,
            "args": {"worker": self.name,
                     "process_spec": self.service.pinned()["process"]}})
        print(f"Fleet worker {self.name} hot-swapped to "
              f"{self.service.pinned()} in {swap_s:.2f} s "
              f"({'RESIDENT program reactivated' if resident else 'fresh build'}"
              f"; compile cache: +{c1['hits'] - c0['hits']} hits, "
              f"+{c1['misses'] - c0['misses']} misses)", flush=True)
        return True

    # ------------------------------------------------------------------
    # the loop

    def run(self) -> int:
        """Serve until drained (the controller's DRAIN file, SIGTERM
        routed to `service.drain()`, or the controller removing our
        row). Returns the service's drain exit code (0 idle / 75 with
        checkpointed in-flight work)."""
        try:
            while True:
                if not self._heartbeat():
                    print(f"Fleet worker {self.name}: row removed by "
                          "the controller (declared dead) — stopping "
                          "so requeued work is not double-run",
                          flush=True)
                    return 0
                self._maybe_swap()
                code = self.service.serve(max_beats=self.tick_beats)
                if self.service.drained:
                    return code
        finally:
            self.table.unregister(self.name)
            for svc in self._resident.values():
                svc.close()   # idempotent; includes the active one


def main(argv=None) -> int:
    import argparse
    import faulthandler
    import signal
    import sys

    # fleet ops: a wedged worker can be asked for its Python stacks
    # with SIGUSR1 (lands in the worker's log), and a native crash
    # (SIGSEGV/SIGABRT) dumps tracebacks instead of dying silently
    faulthandler.enable()
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    p = argparse.ArgumentParser(
        prog="rram-sweep-fleet-worker",
        description="one fleet worker: a pod-backed SweepService with "
                    "registration, heartbeats, and hot program swap "
                    "(see serve/fleet/worker.py)")
    p.add_argument("--fleet-dir", required=True)
    p.add_argument("--name", required=True,
                   help="worker id — the table row and service dir "
                        "name; restart with the SAME name to resume "
                        "its checkpointed work")
    p.add_argument("--solver", required=True,
                   help="solver prototxt for the default net")
    p.add_argument("--net", action="append", default=[],
                   metavar="NAME=SOLVER",
                   help="extra net alias a swap may re-pin to "
                        "(repeatable)")
    p.add_argument("--net-name", default=None,
                   help="name the default --solver registers under "
                        "(default: file basename)")
    p.add_argument("--fault-process", default=None)
    p.add_argument("--tiles", default=None)
    p.add_argument("--dtype-policy", default=None)
    p.add_argument("--lanes", type=int, default=8)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--default-iters", type=int, default=100)
    p.add_argument("--max-retries", type=int, default=1)
    p.add_argument("--slo-seconds", type=float, default=0.0)
    p.add_argument("--admission", default="queue",
                   choices=["queue", "reject"])
    p.add_argument("--poll-interval", type=float, default=0.5)
    p.add_argument("--pipeline-depth", type=int, default=0)
    p.add_argument("--mesh", default="",
                   help="config mesh for THIS worker's lane pool "
                        "(workers may run different topologies)")
    p.add_argument("--trace", action="store_true")
    p.add_argument("--save-fault-results", action="store_true")
    p.add_argument("--allow-inject", action="store_true",
                   help="TEST HOOK: honor requests' inject_nan field")
    p.add_argument("--cache-dir", default=None,
                   help="persistent compile/dataset cache root "
                        "(RRAM_TPU_CACHE_DIR) — what makes a hot swap "
                        "a cache hit instead of a recompile")
    p.add_argument("--tick-beats", type=int,
                   default=DEFAULT_TICK_BEATS,
                   help="service beats between heartbeats/swap checks")
    p.add_argument("--resident-programs", type=int, default=2,
                   help="how many pinned program sets stay PARKED in "
                        "memory (compiled executables + device state) "
                        "so a swap back is a pure re-activation; size "
                        "to the tenant shapes this worker oscillates "
                        "between and the accelerator memory to spare")
    args = p.parse_args(argv)

    if args.cache_dir:
        # PRIVATE per-worker snapshot of the shared warm cache
        # (cache.clone_cache): N live jax processes sharing one
        # persistent compilation cache intermittently corrupts
        # deserialized executables, so each worker hard-links the
        # completed entries into its own root at startup — the warm
        # hits (and the hot-swap-as-cache-hit contract) survive, the
        # cross-process races do not. One call then arms BOTH caches:
        # the explicit root is latched as the active cache dir and
        # dataset_cache_dir() resolves from it.
        from ... import cache as perf_cache
        private = os.path.join(os.path.abspath(args.cache_dir),
                               f"worker-{args.name}")
        n = perf_cache.clone_cache(args.cache_dir, private)
        print(f"Fleet worker {args.name}: private cache snapshot at "
              f"{private} ({n} entries linked)", flush=True)
        # min_compile_time_s=0.05: only REAL programs (the chunk
        # executables the hot swap re-places) ride the cache — the
        # zeroed default would also cache every eager tiny-op
        # executable, whose deserialization intermittently segfaults
        # on this jaxlib (see enable_compilation_cache)
        perf_cache.enable_compilation_cache(private,
                                            min_compile_time_s=0.05)

    nets = {}
    for spec in args.net:
        if "=" not in spec:
            p.error(f"--net {spec!r} must be NAME=SOLVER")
        nname, path = spec.split("=", 1)
        nets[nname] = path

    worker = FleetWorker(
        args.fleet_dir, args.name, args.solver, nets=nets,
        fault_process=args.fault_process, tile_spec=args.tiles,
        dtype_policy=args.dtype_policy, net_name=args.net_name,
        tick_beats=args.tick_beats,
        resident_programs=args.resident_programs,
        service_kw=dict(
            lanes=args.lanes, chunk=args.chunk,
            default_iters=args.default_iters,
            max_retries=args.max_retries,
            slo_seconds=args.slo_seconds, admission=args.admission,
            poll_interval_s=args.poll_interval,
            pipeline_depth=args.pipeline_depth,
            mesh=args.mesh or None, trace=args.trace,
            save_fault_results=args.save_fault_results,
            allow_inject=args.allow_inject))

    def _on_signal(signum, frame):
        worker.service.drain()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(f"Fleet worker {worker.name} up: "
          f"{json.dumps(worker.service.pinned())}", flush=True)
    code = worker.run()
    sys.stdout.flush()
    return code


if __name__ == "__main__":
    import sys
    sys.exit(main())

"""Pinned-program routing — pure host-side decisions, no devices.

A request may pin any subset of (process, dtype_policy, net, tiles);
a worker row registers the canonical value for ALL of them. The
router's contract:

- a pin the request does not name matches ANY worker (an unpinned
  request is happy wherever it lands — the default-physics tenant);
- a named pin must equal the worker's registered canonical value
  (callers canonicalize spellings BEFORE routing — the controller
  runs request pins through FaultSpec/TileSpec when the framework is
  importable, and the worker registered canonical strings);
- among matching workers, the least-loaded wins (fewest
  occupied lanes + queued configs, ties by worker id — deterministic,
  so a replayed stream routes identically);
- when NOTHING matches, the least-loaded *swappable* worker is picked
  as the hot-swap victim: its compiled program set is re-pinned to
  the request's demands (unnamed pins keep the victim's current
  value), which the AOT compile cache turns into a re-place +
  cache-hit, not a cold start. Workers already mid-swap count as
  matching their swap TARGET, so a burst of same-pin requests piles
  onto one swap instead of flipping the whole fleet.

Every function is a pure function of plain dicts so the scheduler
logic unit-tests without devices (tests/test_fleet.py).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .table import PIN_KEYS


def request_pins(req: dict) -> Dict[str, str]:
    """The pins a request names (canonical-spelling responsibility
    lies with the caller), keyed by PIN_KEYS subset."""
    return {k: str(req[k]) for k in PIN_KEYS
            if req.get(k) is not None}


def effective_pins(row: dict) -> Dict[str, str]:
    """The pins a worker row currently answers for: its swap TARGET
    while a swap is pending (requests routed today are admitted by
    the post-swap service), its registered set otherwise."""
    pend = row.get("pending_swap")
    if isinstance(pend, dict) and pend:
        return {str(k): str(v) for k, v in pend.items()}
    return {str(k): str(v)
            for k, v in (row.get("pinned") or {}).items()}


def worker_matches(pins: Dict[str, str], row: dict) -> bool:
    """True when every pin the request names equals the worker's
    effective value."""
    mine = effective_pins(row)
    return all(mine.get(k) == v for k, v in pins.items())


def worker_load(row: dict) -> int:
    """Occupied lanes + queued configs — the least-loaded metric for
    both match choice and swap-victim choice."""
    return (int(row.get("occupied_lanes", 0))
            + int(row.get("pending_configs", 0)))


def _least_loaded(rows: Dict[str, dict], candidates: List[str]
                  ) -> Optional[str]:
    if not candidates:
        return None
    return min(candidates, key=lambda w: (worker_load(rows[w]), w))


def pick_worker(pins: Dict[str, str], rows: Dict[str, dict]
                ) -> Optional[str]:
    """The least-loaded worker matching every named pin; None when no
    worker matches."""
    return _least_loaded(rows, [w for w, r in rows.items()
                                if worker_matches(pins, r)])


def pick_swap_victim(pins: Dict[str, str], rows: Dict[str, dict]
                     ) -> Optional[str]:
    """The least-loaded worker NOT already mid-swap — swapping a
    worker whose queue is already promised to a different program set
    would strand those requests behind a second recompile. A request
    pinning a NET is only swapped onto workers that registered that
    net among their known solvers (`nets` row field; a row without
    one accepts anything, the pre-nets compatibility case)."""
    want_net = pins.get("net")

    def can_serve(r: dict) -> bool:
        if r.get("pending_swap"):
            return False
        nets = r.get("nets")
        return (want_net is None or nets is None
                or want_net in nets)

    return _least_loaded(rows, [w for w, r in rows.items()
                                if can_serve(r)])


def swap_target(pins: Dict[str, str], row: dict) -> Dict[str, str]:
    """The full pinned set the victim swaps to: the request's named
    pins over the victim's current values (a request pinning only
    `process` keeps the victim's dtype_policy/net/tiles)."""
    target = {str(k): str(v)
              for k, v in (row.get("pinned") or {}).items()}
    target.update(pins)
    return target


def route(pins: Dict[str, str], rows: Dict[str, dict]
          ) -> Tuple[Optional[str], Optional[Dict[str, str]]]:
    """(worker id, swap pinned-set or None). (None, None) when the
    table is empty or every worker is mid-swap to something else —
    the request stays pending (and the scaler sees the backlog)."""
    wid = pick_worker(pins, rows)
    if wid is not None:
        return wid, None
    victim = pick_swap_victim(pins, rows)
    if victim is None:
        return None, None
    return victim, swap_target(pins, rows[victim])


def requeue_plan(assignments: Dict[str, dict], dead: List[str],
                 finished: Dict[str, str]) -> List[str]:
    """Which request ids a dead-worker sweep must requeue: assigned to
    a dead worker AND not already terminal in the dead worker's spool
    (`finished` maps request id -> terminal status for work the worker
    completed before dying — that work harvests normally; re-running
    it would break the byte-identity contract for no durability
    gain). Pure bookkeeping — tests/test_fleet.py pins it."""
    dead_set = set(dead)
    return sorted(rid for rid, a in assignments.items()
                  if a.get("worker") in dead_set
                  and rid not in finished)

"""``caffe fleet top`` — a curses-free live terminal fleet view.

Polls the controller's ``<fleet>/metrics.prom`` rollup (rewritten
atomically every beat) plus the worker table rows, and repaints one
plain-text frame per interval with ANSI clear-screen — no curses, no
external dependencies, works over ssh and in CI (``--once`` prints a
single frame and exits, which is how the tests drive it).

The view is read-only: it never touches the spool, the sockets, or
the table — killing it mid-frame cannot perturb the fleet, and a
monitored run stays byte-identical to an unmonitored one.

    caffe fleet top --fleet-dir /runs/fleet
    caffe fleet top --fleet-dir /runs/fleet --once   # one frame (CI)
"""
from __future__ import annotations

import os
import time

CLEAR = "\x1b[2J\x1b[H"


def _load_rollup(fleet_dir):
    """Parsed rollup samples, or None when no rollup exists yet."""
    from ...observe.metrics_registry import parse_exposition
    path = os.path.join(fleet_dir, "metrics.prom")
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return parse_exposition(fh.read())
    except (OSError, ValueError):
        return None


def _load_rows(fleet_dir):
    from .table import WorkerTable
    try:
        return WorkerTable(fleet_dir).rows()
    except OSError:
        return {}


def _get(samples, name, default=0.0, **labels):
    key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
    val = samples.get(key)
    return default if val is None else val


def _fmt_age(seconds):
    if seconds < 100:
        return f"{seconds:.1f}s"
    if seconds < 6000:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def render_frame(fleet_dir, samples, rows, now=None):
    """One frame of the fleet view as a string (pure; unit-testable)."""
    now = time.time() if now is None else now
    lines = []
    if samples is None:
        lines.append(f"fleet {fleet_dir}")
        lines.append("no rollup yet (metrics.prom absent) — is the "
                     "controller beating?")
        if rows:
            lines.append(f"worker table has {len(rows)} row(s): "
                         + ", ".join(sorted(rows)))
        return "\n".join(lines) + "\n"

    beat = int(_get(samples, "rram_fleet_beat"))
    workers = int(_get(samples, "rram_fleet_workers"))
    lanes = int(_get(samples, "rram_fleet_lanes"))
    occupied = int(_get(samples, "rram_fleet_occupied_lanes"))
    occ = _get(samples, "rram_fleet_occupancy_ratio")
    backlog = _get(samples, "rram_fleet_backlog_iters")
    ema = _get(samples, "rram_fleet_backlog_ema")
    pending = int(_get(samples, "rram_fleet_pending_requests"))
    assigned = int(_get(samples, "rram_fleet_assigned_requests"))
    burn = _get(samples, "rram_fleet_slo_burn_rate")
    p50 = _get(samples, "rram_fleet_turnaround_seconds", None,
               quantile="0.5")
    p99 = _get(samples, "rram_fleet_turnaround_seconds", None,
               quantile="0.99")

    lines.append(f"fleet {fleet_dir}  beat {beat}  "
                 f"workers {workers}  lanes {occupied}/{lanes} "
                 f"({occ:.0%} occupied)")
    lat = "p50 —  p99 —" if p50 is None else \
        f"p50 {p50:.2f}s  p99 {p99:.2f}s"
    lines.append(f"backlog {backlog:g} iters (ema {ema:g})  "
                 f"pending {pending}  in-flight {assigned}  "
                 f"{lat}  slo burn {burn:.2f}")
    # crossbar health plane: shown only when any worker reports wear
    # censuses (rram_health_reporting_workers > 0)
    reporting = _get(samples, "rram_health_reporting_workers", 0.0)
    if reporting:
        bf = _get(samples, "rram_health_broken_frac_max", None)
        rul = _get(samples, "rram_health_rul_iters_min", None)
        wear = "—" if bf is None else f"{bf:.1%}"
        horizon = "—" if rul is None else f"{rul:g} iters"
        lines.append(f"wear: worst tile {wear} broken  "
                     f"min RUL {horizon}  "
                     f"({int(reporting)} worker(s) reporting)")

    firing = sorted(
        dict(labels).get("alert", "")
        for (name, labels), value in samples.items()
        if name == "rram_alert_firing" and value >= 1)
    if firing:
        lines.append("ALERTS FIRING: " + ", ".join(firing))
    else:
        lines.append("alerts: none firing")

    lines.append("")
    lines.append(f"{'WORKER':<10}{'AGE':>6}{'LANES':>7}{'PEND':>6}"
                 f"{'ACTIVE':>8}{'STEP/S':>9}{'SWAPS':>7}{'OCC':>6}"
                 f"{'WEAR':>7}  PINNED")
    wids = sorted(set(
        dict(labels).get("worker", "")
        for (name, labels), _ in samples.items()
        if name == "rram_worker_up") | set(rows))
    for wid in wids:
        row = rows.get(wid) or {}
        age = now - float(row.get("heartbeat_time", now))
        lanes_w = int(_get(samples, "rram_worker_lanes",
                           row.get("lanes", 0), worker=wid))
        occ_w = int(_get(samples, "rram_worker_occupied_lanes",
                         row.get("occupied_lanes", 0), worker=wid))
        pend_w = int(_get(samples, "rram_worker_pending_configs",
                          row.get("pending_configs", 0), worker=wid))
        active = int(_get(samples, "rram_worker_active_requests", 0,
                          worker=wid))
        sps = _get(samples, "rram_worker_steps_per_sec",
                   row.get("steps_per_sec", 0.0), worker=wid)
        swaps = int(_get(samples, "rram_worker_swap_total",
                         row.get("swap_count", 0), worker=wid))
        occr = _get(samples, "rram_worker_occupancy_ratio", 0.0,
                    worker=wid)
        wear_bf = _get(samples, "rram_worker_health_broken_frac_max",
                       None, worker=wid)
        if wear_bf is None:
            snap = (row.get("stats") or {}).get("health") or {}
            wear_bf = snap.get("broken_frac_max")
        wear = "—" if wear_bf is None else f"{float(wear_bf):.1%}"
        pinned = row.get("pinned") or {}
        pin = ",".join(f"{k}={pinned[k]}" for k in
                       ("process", "net", "tiles", "dtype_policy")
                       if pinned.get(k))
        lines.append(f"{wid:<10}{_fmt_age(age):>6}"
                     f"{f'{occ_w}/{lanes_w}':>7}{pend_w:>6}"
                     f"{active:>8}{sps:>9.1f}{swaps:>7}"
                     f"{occr:>6.0%}{wear:>7}  {pin}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="caffe fleet top",
        description="live fleet view over the controller's "
                    "metrics.prom rollup (see serve/fleet/top.py)")
    p.add_argument("--fleet-dir", required=True)
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between repaints")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (CI / scripting)")
    p.add_argument("--frames", type=int, default=0,
                   help="stop after N frames (test hook); 0 = forever")
    args = p.parse_args(argv)

    fleet = os.path.abspath(args.fleet_dir)
    frames = 0
    try:
        while True:
            frame = render_frame(fleet, _load_rollup(fleet),
                                 _load_rows(fleet))
            if args.once:
                print(frame, end="", flush=True)
                return 0
            print(CLEAR + frame, end="", flush=True)
            frames += 1
            if args.frames and frames >= args.frames:
                return 0
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        print()
        return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())

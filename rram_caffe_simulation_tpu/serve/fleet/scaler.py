"""Backlog-EMA worker scaling — the admission controller's projection,
lifted fleet-wide.

`SweepService` projects a request's turnaround as backlog
lane-iterations over a dispatch-rate EMA; the fleet scaler runs the
same arithmetic over the WHOLE fleet each controller beat:

    projected_s = total backlog lane-iters / aggregate fleet rate

and steers the worker count toward keeping that projection inside the
target window:

- projection > `target_seconds` for `up_after` consecutive beats (and
  the backlog is real, not one straggler request) -> scale UP;
- projection < `down_factor * target_seconds` for `down_after`
  consecutive beats AND at least one worker is fully idle -> scale
  DOWN (draining a busy worker would requeue work just to save a
  process);
- while NO rate has been measured yet (cold fleet), pending work with
  zero workers scales up — the bootstrap case.

The hysteresis counters make the decision a pure fold over observed
beats: `decide()` is deterministic given the observation sequence, so
tests/test_fleet.py pins the exact scale-up/-down beat. No devices,
no framework imports.
"""
from __future__ import annotations

from typing import Optional


class BacklogScaler:
    """One instance per FleetController; `decide()` once per beat."""

    def __init__(self, target_seconds: float = 60.0,
                 min_workers: int = 1, max_workers: int = 4,
                 up_after: int = 3, down_after: int = 10,
                 down_factor: float = 0.25, ema: float = 0.3):
        if not (0 < float(ema) <= 1):
            raise ValueError(f"ema {ema!r} must be in (0, 1]")
        if int(min_workers) < 0 or int(max_workers) < int(min_workers):
            raise ValueError(
                f"worker bounds ({min_workers}, {max_workers}) must "
                "satisfy 0 <= min <= max")
        self.target_seconds = float(target_seconds)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.up_after = max(int(up_after), 1)
        self.down_after = max(int(down_after), 1)
        self.down_factor = float(down_factor)
        self.ema = float(ema)
        self.projected_s: Optional[float] = None   # smoothed projection
        self._over = 0
        self._under = 0

    def observe(self, backlog_iters: float, rate_iters_per_s: float
                ) -> Optional[float]:
        """Fold one beat's fleet totals into the projection EMA.
        Returns the smoothed projection (None until a rate exists)."""
        if rate_iters_per_s <= 0:
            return self.projected_s
        raw = float(backlog_iters) / float(rate_iters_per_s)
        self.projected_s = (raw if self.projected_s is None
                            else (1 - self.ema) * self.projected_s
                            + self.ema * raw)
        return self.projected_s

    def decide(self, backlog_iters: float, rate_iters_per_s: float,
               workers: int, idle_workers: int = 0) -> int:
        """+1 (spawn), -1 (drain one idle worker), or 0. `workers`
        counts live workers, `idle_workers` those with zero occupied
        lanes and zero queued configs."""
        projected = self.observe(backlog_iters, rate_iters_per_s)
        # bootstrap: work waiting and nobody to run it
        if workers < self.min_workers \
                or (workers == 0 and backlog_iters > 0):
            self._over = self._under = 0
            return 1 if workers < self.max_workers else 0
        if projected is None:
            return 0
        if projected > self.target_seconds and backlog_iters > 0:
            self._over += 1
            self._under = 0
            if self._over >= self.up_after \
                    and workers < self.max_workers:
                self._over = 0
                return 1
            return 0
        self._over = 0
        if projected < self.down_factor * self.target_seconds:
            self._under += 1
            if self._under >= self.down_after \
                    and workers > self.min_workers and idle_workers > 0:
                self._under = 0
                return -1
            return 0
        self._under = 0
        return 0

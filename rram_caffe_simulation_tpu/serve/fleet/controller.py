"""FleetController — one durable spool, N pod-backed workers.

The fleet lifts the single-service architecture one level (ROADMAP
item 2): the durable filesystem spool becomes a SHARED queue at
``<fleet>/spool``, and each worker is a full `SweepService` (warm
vectorized lane pool, possibly on its own mesh topology) living under
``<fleet>/workers/<wid>/`` with its pinned program set registered in
the worker table (table.py). The controller is pure host-side
scheduling — it never touches a device:

- **route** (router.py): each pending fleet request moves into the
  matching warm worker's own spool (an atomic cross-directory copy +
  fleet-spool claim), least-loaded first; when no worker matches the
  request's (process, dtype_policy, net, tiles) pins, the least-loaded
  swappable worker gets a hot-swap command — the AOT compile cache +
  fault-process/tile registry seams make the swap a re-place +
  compile-cache hit, not a cold start (the worker proves it with the
  cache counter delta on its `swap` record);
- **harvest**: a worker's terminal spool file folds back into the
  fleet spool's done/, so `ServeClient` against the fleet directory
  sees one queue end to end;
- **reap**: a worker whose heartbeat goes stale past
  `heartbeat_timeout_s` is declared dead (`worker` record), its
  in-flight requests REQUEUE onto the fleet spool (at-least-once —
  the PR 6 completion contract, lifted one level), and its row leaves
  the table;
- **scale** (scaler.py): the admission controller's projected-backlog
  EMA, computed fleet-wide, spawns workers from `--worker-cmd` (up to
  `--max-workers`) or drains an idle one.

Run it with ``python -m rram_caffe_simulation_tpu.serve.fleet`` next
to N ``...serve.fleet.worker`` processes sharing the fleet directory.
The controller itself needs no accelerator stack — request-pin
canonicalization lazily imports the fault registry and falls back to
raw string comparison when the framework is absent (a monitoring
host can run it against a shared filesystem).
"""
from __future__ import annotations

import collections
import json
import math
import os
import shlex
import socket
import subprocess
import time
import zlib
from typing import Dict, List, Optional

_HOSTNAME = socket.gethostname()

from ..spool import Spool, _atomic_write, normalize_request
from .alerts import AlertEngine
from .router import (request_pins, requeue_plan, route, worker_load)
from .scaler import BacklogScaler
from .table import WorkerTable

#: fields the controller strips when copying a request between spools
#: (stale bookkeeping from a previous claimant must not ride along);
#: `attempt` is re-stamped explicitly on every delivery
_BOOKKEEPING = ("cfg_ids", "iters_granted", "status", "worker",
                "attempt", "submit_seen", "state")

#: scrape-retry backoff cap, in beats (capped exponential: 1, 2, 4, 8)
_SCRAPE_BACKOFF_CAP = 8


def _append_jsonl(path: str, rec: dict):
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def canonicalize_pins(pins: Dict[str, str]) -> Dict[str, str]:
    """Run request pins through the registry canonicalizers so any
    equivalent spelling routes to the same worker. Lazy imports: with
    the framework absent (a bare monitoring host) raw strings compare
    as-is — workers registered canonical spellings, so canonical
    requests still route. An unparseable spec raises ValueError (the
    request is rejected at the fleet door, same contract as service
    admission)."""
    out = dict(pins)
    if "process" in out:
        try:
            from ...fault.processes import FaultSpec
        except ImportError:
            pass
        else:
            out["process"] = FaultSpec.parse(out["process"]).canonical()
    if "tiles" in out:
        try:
            from ...fault.mapping import TileSpec
        except ImportError:
            pass
        else:
            out["tiles"] = TileSpec.parse(out["tiles"]).canonical()
    return out


class FleetController:
    """The scheduling head of one fleet directory."""

    def __init__(self, fleet_dir: str, *,
                 heartbeat_timeout_s: float = 10.0,
                 poll_interval_s: float = 0.5,
                 default_iters: int = 100,
                 scaler: Optional[BacklogScaler] = None,
                 worker_cmd: Optional[str] = None,
                 alert_rules: Optional[list] = None,
                 scrape_sockets: bool = True,
                 chaos=None):
        self.dir = os.path.abspath(fleet_dir)
        os.makedirs(self.dir, exist_ok=True)
        #: poison quarantine (ISSUE 20): unparseable spool / worker-
        #: table files move here instead of crashing the beat loop
        self.poison_dir = os.path.join(self.dir, "poison")
        self.spool = Spool(os.path.join(self.dir, "spool"),
                           poison_dir=self.poison_dir)
        self.table = WorkerTable(self.dir,
                                 poison_dir=self.poison_dir)
        #: optional deterministic failure-injection plan
        #: (serve/fleet/chaos.py) — None in production
        self.chaos = chaos
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.default_iters = int(default_iters)
        self.scaler = scaler
        self.worker_cmd = worker_cmd
        self.metrics_path = os.path.join(self.dir, "fleet.jsonl")
        #: the watchtower (ISSUE 16): a fleet-wide Prometheus rollup
        #: rewritten every beat, plus a declarative alert rule engine
        #: whose firing/resolved transitions land as schema-validated
        #: `alert` records on fleet.jsonl
        self.rollup_path = os.path.join(self.dir, "metrics.prom")
        self.alert_engine = AlertEngine(alert_rules)
        self.scrape_sockets = bool(scrape_sockets)
        #: monotonic watchtower counters (persisted so the delta rules
        #: survive a controller restart)
        self._deaths_total = 0
        self._swap_cmds_total = 0
        self._quarantine_total = 0
        self._poison_total = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._last_scale_decision = 0
        #: per-worker CONSECUTIVE scrape-failure streaks (sticky until
        #: a scrape succeeds or the worker is reaped) + the beat each
        #: backed-off worker may be scraped again
        self._scrape_failures: Dict[str, int] = {}
        self._scrape_retry_beat: Dict[str, int] = {}
        #: set when a state/rollup write failed (ENOSPC, EIO): the run
        #: loop degrades to drain-with-checkpoint instead of crash-
        #: looping on a full disk
        self._force_drain = False
        #: harvested request turnarounds (bounded) -> rollup quantiles
        self._latencies = collections.deque(maxlen=4096)
        self._beats = 0
        #: request id -> {"worker", "attempt"} for routed, unharvested
        #: requests (persisted in state.json across restarts)
        self.assignments: Dict[str, dict] = {}
        #: worker id -> swap target pins, while a swap command is out
        self.pending_swaps: Dict[str, Dict[str, str]] = {}
        self._next_ordinal = 0
        self._spawned: Dict[str, subprocess.Popen] = {}
        self._worker_spools: Dict[str, Spool] = {}
        #: routed-but-unservable backlog measured by the LAST routing
        #: pass — the scaler reads this instead of re-parsing every
        #: pending file a second time per beat
        self._pending_backlog_iters = 0
        if os.path.exists(self._state_path()):
            self._load_state()
        # crash-window recovery, in journal order. First finish any
        # rename walk that died between its atomic destination write
        # and its source remove (claim / requeue / finish caught by a
        # SIGKILL): the destination is the commit point, so
        # resolve_dual completes the move instead of double-seeing
        # the request.
        for rid in self.spool.dual_ids():
            self.spool.resolve_dual(rid)
        # A request CLAIMED in a beat that died before its state write
        # is active in the fleet spool (the claim persisted the
        # worker/attempt fields) but absent from the loaded
        # assignments — rebuild those entries, or the request would
        # never harvest and never requeue.
        for req in self.spool.active():
            rid = req.get("id")
            if rid and rid not in self.assignments \
                    and req.get("worker"):
                self.assignments[rid] = {
                    "worker": str(req["worker"]),
                    "attempt": int(req.get("attempt", 1))}
        # And the mirror image: a loaded assignment whose request is
        # no longer active (harvested/requeued after the last state
        # write) is stale — drop it, or _harvest could try to finish
        # an already-terminal request (the exactly-once gap).
        for rid in list(self.assignments):
            if self.spool.state_of(rid) != "active":
                del self.assignments[rid]

    # ------------------------------------------------------------------
    # persistence + records

    def _state_path(self) -> str:
        return os.path.join(self.dir, "state.json")

    def _load_state(self):
        try:
            with open(self._state_path()) as f:
                state = json.load(f)
        except ValueError as e:
            # a torn commit record (SIGKILL mid-write on a filesystem
            # without atomic rename, or a chaos injection): quarantine
            # the bytes and rebuild from the spool — the active files
            # carry worker+attempt, so nothing is lost
            os.makedirs(self.poison_dir, exist_ok=True)
            dst = os.path.join(self.poison_dir, "state.json")
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = os.path.join(self.poison_dir, f"state.json.{n}")
            try:
                os.replace(self._state_path(), dst)
                self._poison_total += 1
            except OSError:
                pass
            print(f"Fleet controller: torn state.json quarantined to "
                  f"{dst} ({e}); rebuilding from the spool",
                  flush=True)
            return
        self.assignments = dict(state.get("assignments", {}))
        self.pending_swaps = dict(state.get("pending_swaps", {}))
        self._next_ordinal = int(state.get("next_ordinal", 0))
        counters = state.get("watchtower") or {}
        self._deaths_total = int(counters.get("deaths", 0))
        self._swap_cmds_total = int(counters.get("swap_cmds", 0))
        self._quarantine_total = int(counters.get("quarantines", 0))
        self._poison_total = int(counters.get("poisons", 0))
        self._scale_ups = int(counters.get("scale_ups", 0))
        self._scale_downs = int(counters.get("scale_downs", 0))

    def _write_state(self):
        payload = {
            "schema_version": 1,
            "assignments": self.assignments,
            "pending_swaps": self.pending_swaps,
            "next_ordinal": self._next_ordinal,
            "watchtower": {
                "deaths": self._deaths_total,
                "swap_cmds": self._swap_cmds_total,
                "quarantines": self._quarantine_total,
                "poisons": self._poison_total,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
            },
        }
        if self.chaos is not None:
            # a stage-"commit" kill tears the record at a seeded byte
            # offset and raises — the restart path must recover
            self.chaos.tear_commit(self._state_path(), payload)
        _atomic_write(self._state_path(), payload)

    def _emit(self, wid: str, event: str, **kw):
        from ...observe import make_worker_record
        kw = {k: v for k, v in kw.items() if v is not None}
        _append_jsonl(self.metrics_path,
                      make_worker_record(self._beats, wid, event, **kw))

    def _worker_spool(self, wid: str) -> Spool:
        sp = self._worker_spools.get(wid)
        if sp is None:
            sp = Spool(os.path.join(self.table.worker_dir(wid),
                                    "spool"))
            self._worker_spools[wid] = sp
        return sp

    # ------------------------------------------------------------------
    # the beat

    def beat(self) -> dict:
        """One scheduling pass: reap dead workers, harvest terminal
        requests, route pending ones, apply a scale decision. Returns
        a summary dict (what the CLI prints at --verbose).

        The beat is an idempotent journaled transaction (ISSUE 20):
        every state move is an atomic rename whose destination is the
        commit point (claim carries worker+attempt, finish carries
        the terminal payload), interrupted moves are completed by
        `resolve_dual` on the next pass, and `state.json` — written
        LAST — is only a cache of what the spool already proves. A
        SIGKILL at any byte offset mid-beat therefore recovers on
        restart with no lost, orphaned, or double-routed request
        (chaos-guarded by scripts/check_fleet_chaos.py)."""
        self._beats += 1
        if self.chaos is not None:
            self.chaos.begin_beat(self)
        self._heal_spool()
        rows = self.table.rows()
        dead = self._reap_poisoned()
        self._reconcile_swaps(rows)
        dead += self._reap(rows)
        for wid in dead:
            rows.pop(wid, None)
        self._checkpoint("reap")
        harvested = self._harvest()
        self._checkpoint("harvest")
        self._redeliver()
        routed = self._route_pending(rows)
        self._checkpoint("route")
        scale = self._apply_scale(rows)
        try:
            alerts = self._watchtower(rows)
        except OSError as e:
            alerts = []
            self._degrade(e)
        try:
            self._write_state()
        except OSError as e:
            self._degrade(e)
        return {"beat": self._beats, "workers": sorted(rows),
                "dead": dead, "harvested": harvested,
                "routed": routed, "scale": scale,
                "pending": len(self.spool.pending_ids()),
                "assigned": len(self.assignments),
                "alerts": alerts,
                "firing": self.alert_engine.active()}

    def _checkpoint(self, stage: str):
        """Chaos hook: a seeded controller_kill strikes between beat
        stages here (no-op without an attached plan)."""
        if self.chaos is not None:
            self.chaos.maybe_kill(stage)

    def _heal_spool(self):
        """Complete any fleet-spool rename that a previous crash left
        halfway (the request file present under two state dirs), and
        keep the in-memory assignments consistent with the outcome."""
        for rid in self.spool.dual_ids():
            state = self.spool.resolve_dual(rid)
            if state == "active":
                req = self.spool.read(rid)
                if req and req.get("worker") \
                        and rid not in self.assignments:
                    self.assignments[rid] = {
                        "worker": str(req["worker"]),
                        "attempt": int(req.get("attempt", 1))}
            elif state in ("pending", "done", None):
                self.assignments.pop(rid, None)

    def _reap_poisoned(self) -> List[str]:
        """A worker whose table row was quarantined as unparseable is
        declared dead LOUDLY — same protocol as a missed heartbeat
        (the worker's next heartbeat sees its row gone and exits) —
        instead of silently vanishing with its requests orphaned."""
        dead = []
        for p in self.table.drain_poisoned():
            wid = p["worker"]
            self._poison_total += 1
            self._deaths_total += 1
            self._emit(wid, "dead",
                       reason="worker table row unparseable; "
                              f"quarantined to {p['moved_to']}")
            finished = {}
            wspool = self._worker_spool(wid)
            for rid, a in self.assignments.items():
                if a.get("worker") == wid \
                        and wspool.state_of(rid) == "done":
                    finished[rid] = "done"
            for rid in requeue_plan(self.assignments, [wid], finished):
                self._requeue(rid, wid)
            self.table.remove(wid)
            self.pending_swaps.pop(wid, None)
            self._spawned.pop(wid, None)
            self._scrape_failures.pop(wid, None)
            self._scrape_retry_beat.pop(wid, None)
            dead.append(wid)
        return dead

    def _degrade(self, err: Exception):
        """A failed state/rollup write (ENOSPC, EIO) must not become
        a crash loop: request a fleet drain — workers checkpoint
        their in-flight requests, the run loop exits 75, and the
        operator restarts on a healthy disk to resume."""
        if self._force_drain:
            return
        self._force_drain = True
        print(f"Fleet controller: write failure ({err}); degrading "
              "to drain-with-checkpoint (exit 75 resumes)", flush=True)
        try:
            with open(os.path.join(self.dir, "DRAIN"), "w"):
                pass
        except OSError:
            pass    # even the flag write failed; the in-memory flag
                    # still drains this process

    def _reconcile_swaps(self, rows: Dict[str, dict]):
        """Clear a pending swap once the worker re-registered with the
        target pins, and overlay still-pending targets onto the rows
        so the router matches against what the worker is BECOMING. A
        consumed command WITHOUT the re-pin is the worker's refusal
        protocol (e.g. an unknown net) — drop the overlay so the
        worker is not wedged out of routing and victim selection
        forever (workers publish the new pins BEFORE clearing the
        command, so applied swaps never look like refusals)."""
        for wid, target in list(self.pending_swaps.items()):
            row = rows.get(wid)
            if row is None:
                continue   # reaped or departed; _reap cleans up
            if self.table.read_swap(wid) is None:
                if (row.get("pinned") or {}) != target:
                    self._emit(wid, "swap_refused", pinned=target,
                               reason="worker consumed the command "
                                      "without re-pinning; routing "
                                      "overlay dropped")
                del self.pending_swaps[wid]
            else:
                row["pending_swap"] = target

    def _dead_reason(self, row: dict, now: float) -> Optional[str]:
        """Why this row's worker counts as dead: a vanished same-host
        pid (fast path — a SIGKILL is seen within one beat, and a
        worker busy inside a long swap rebuild is NOT declared dead
        just for missing heartbeats) or a stale heartbeat (the
        cross-host fallback)."""
        idle_s = now - float(row.get("heartbeat_time", 0))
        pid = row.get("pid")
        if pid and row.get("host") == _HOSTNAME:
            try:
                os.kill(int(pid), 0)
            except ProcessLookupError:
                return f"process {pid} is gone"
            except (OSError, ValueError):
                pass
            else:
                # alive but silent: a swap rebuild legitimately blocks
                # heartbeats for a while, so a live pid gets a 10x
                # grace before a wedged worker is finally reaped
                if idle_s > 10 * self.heartbeat_timeout_s:
                    return (f"process {pid} alive but heartbeat "
                            f"stale for {idle_s:.1f} s (10x the "
                            f"{self.heartbeat_timeout_s:g} s timeout)")
                return None
        if idle_s > self.heartbeat_timeout_s:
            return (f"heartbeat stale for {idle_s:.1f} s (timeout "
                    f"{self.heartbeat_timeout_s:g} s)")
        return None

    def _reap(self, rows: Dict[str, dict]) -> List[str]:
        """Declare dead workers (vanished pid / stale heartbeat) and
        requeue their unfinished requests onto the fleet spool
        (at-least-once)."""
        now = time.time()
        reasons = {wid: self._dead_reason(row, now)
                   for wid, row in rows.items()}
        dead = [wid for wid, r in reasons.items() if r is not None]
        for wid in dead:
            self._deaths_total += 1
            self._emit(wid, "dead", reason=reasons[wid],
                       pinned=rows[wid].get("pinned"))
            # work it finished before dying harvests normally; only
            # unfinished assignments requeue
            finished = {}
            wspool = self._worker_spool(wid)
            for rid, a in self.assignments.items():
                if a.get("worker") == wid \
                        and wspool.state_of(rid) == "done":
                    finished[rid] = "done"
            for rid in requeue_plan(self.assignments, [wid], finished):
                self._requeue(rid, wid)
            self.table.remove(wid)
            self.pending_swaps.pop(wid, None)
            self._spawned.pop(wid, None)
            self._scrape_failures.pop(wid, None)
            self._scrape_retry_beat.pop(wid, None)
        return dead

    def _requeue(self, rid: str, wid: str):
        try:
            self.spool.requeue(rid)
        except FileNotFoundError:
            # never claimed / already terminal at fleet level: there
            # is nothing to resume, and a leaked assignment would hold
            # _fleet_idle() False forever
            self.assignments.pop(rid, None)
            return
        # best effort: scrub the dead worker's copy so a restarted
        # process with the same name cannot double-run it
        wspool = self._worker_spool(wid)
        for state in ("pending", "active"):
            try:
                os.remove(wspool._path(state, rid))
            except OSError:
                pass
        del self.assignments[rid]
        self._emit(wid, "requeued", request=rid,
                   reason="worker died with the request in flight; "
                          "requeued onto survivors (at-least-once)")

    def _harvest(self) -> List[str]:
        """Fold workers' terminal spool files into the fleet done/.

        Exactly-once: the fleet-level terminal record commits at most
        once per (request, attempt). A request already terminal at
        fleet level (a crashed controller's finish committed before
        its state write) just drops its stale assignment, and a done
        file stamped with a DIFFERENT attempt (debris of an earlier
        at-least-once retry) never completes the current one."""
        done = []
        for rid, a in list(self.assignments.items()):
            wid = a["worker"]
            if self.spool.state_of(rid) == "done":
                # the terminal record already committed — dedup, do
                # not land a second one
                del self.assignments[rid]
                continue
            req = self._worker_spool(wid).read(rid)
            if req is None or req.get("state") != "done":
                continue
            if int(req.get("attempt", a["attempt"])) \
                    != int(a["attempt"]):
                continue
            payload = {k: req[k] for k in
                       ("status", "results", "latency_s", "reason")
                       if req.get(k) is not None}
            payload["worker"] = wid
            payload["attempt"] = int(a["attempt"])
            if payload.get("latency_s") is not None:
                self._latencies.append(float(payload["latency_s"]))
            try:
                self.spool.finish(rid, payload)
            except FileNotFoundError:
                # requeued out from under us (e.g. the worker was
                # reaped this very beat): the new attempt owns the
                # request now
                continue
            del self.assignments[rid]
            done.append(rid)
        return done

    def _redeliver(self):
        """Heal the claim->copy crash window: an assignment whose
        worker has NO copy of the request in any spool state means
        the controller died between the fleet-spool claim (the commit
        record) and the worker-spool submit — deliver the copy now.
        The submit is refused on a duplicate id, so delivery stays
        at-most-once per attempt."""
        for rid, a in list(self.assignments.items()):
            wid = a["worker"]
            wspool = self._worker_spool(wid)
            if wspool.state_of(rid) is not None:
                continue
            raw = self.spool.read(rid)
            if raw is None or raw.get("state") != "active":
                continue
            clean = {k: v for k, v in raw.items()
                     if k not in _BOOKKEEPING}
            clean["attempt"] = int(a["attempt"])
            try:
                wspool.submit(clean)
            except ValueError:
                continue
            self._emit(wid, "assigned", request=rid,
                       reason="redelivered: a controller crash "
                              "landed between the claim and the "
                              "worker copy")

    def _route_pending(self, rows: Dict[str, dict]) -> List[str]:
        routed = []
        self._pending_backlog_iters = 0
        for rid in self.spool.pending_ids():
            try:
                raw = self.spool.read(rid)
                if raw is None:
                    continue
                req = normalize_request(dict(raw, id=rid), 0)
                pins = canonicalize_pins(request_pins(req))
            except ValueError as e:
                self._quarantine_total += 1
                self.spool.quarantine(rid, f"invalid request: {e}")
                continue
            wid, swap = route(pins, rows)
            if wid is None:
                # no (swappable) worker yet; the scaler sees the
                # stranded lane-iterations this same beat
                self._pending_backlog_iters += (
                    int(req.get("iters") or self.default_iters)
                    * len(req.get("configs") or []))
                continue
            if swap is not None:
                self._swap_cmds_total += 1
                self.table.command_swap(wid, swap)
                self.pending_swaps[wid] = swap
                rows[wid] = dict(rows[wid], pending_swap=swap)
                self._emit(wid, "swap_requested", request=rid,
                           pinned=swap)
            # journaled transaction order: the fleet-spool CLAIM (an
            # atomic pending->active rename carrying worker+attempt)
            # is the commit record for this routing decision, and the
            # worker-spool copy follows it. A crash between the two
            # re-delivers via _redeliver; the old order (copy first)
            # could DOUBLE-ROUTE — a controller killed between copy
            # and claim would re-route the still-pending request to a
            # different worker while the first copy kept running.
            attempt = int(raw.get("requeues", 0)) + 1
            self.spool.claim(rid, {"worker": wid, "attempt": attempt})
            self.assignments[rid] = {"worker": wid, "attempt": attempt}
            self._checkpoint("claim")
            clean = {k: v for k, v in req.items()
                     if k not in _BOOKKEEPING}
            clean["attempt"] = attempt
            try:
                self._worker_spool(wid).submit(clean)
            except ValueError as e:
                # the worker already knows this id (e.g. a crashed
                # controller re-routing after the copy landed): treat
                # as assigned rather than duplicating the file
                if "already exists" not in str(e):
                    self._quarantine_total += 1
                    self.assignments.pop(rid, None)
                    try:
                        self.spool.finish(
                            rid, {"status": "rejected",
                                  "reason": str(e)})
                    except FileNotFoundError:
                        pass
                    continue
            # the routed load is visible to the next pick immediately
            rows[wid] = dict(
                rows[wid],
                pending_configs=int(rows[wid].get("pending_configs", 0))
                + len(req.get("configs") or []))
            self._emit(wid, "assigned", request=rid)
            routed.append(rid)
        return routed

    # ------------------------------------------------------------------
    # scaling

    def _apply_scale(self, rows: Dict[str, dict]) -> int:
        if self.scaler is None:
            return 0
        rate = sum(float(r.get("steps_per_sec", 0.0))
                   * int(r.get("lanes", 0)) for r in rows.values())
        # unrouted backlog measured by this beat's routing pass (no
        # second read of the pending files), plus the workers' own
        # queued configs
        backlog = self._pending_backlog_iters + sum(
            int(r.get("pending_configs", 0)) * self.default_iters
            for r in rows.values())
        idle = [wid for wid, r in rows.items()
                if worker_load(r) == 0 and not r.get("pending_swap")
                and not any(a["worker"] == wid
                            for a in self.assignments.values())]
        # spawned-but-not-yet-registered workers count toward the
        # fleet size: a jax worker takes seconds-to-minutes to build
        # and register, and re-deciding against the registered count
        # alone would launch a new process every beat of that window
        starting = sum(1 for wid, p in self._spawned.items()
                       if p.poll() is None and wid not in rows)
        decision = self.scaler.decide(backlog, rate,
                                      len(rows) + starting,
                                      idle_workers=len(idle))
        self._last_scale_decision = decision
        if decision > 0:
            self._scale_ups += 1
            self._spawn_worker()
        elif decision < 0 and idle:
            self._scale_downs += 1
            victim = min(idle, key=lambda w: (worker_load(rows[w]), w))
            with open(os.path.join(self.table.worker_dir(victim),
                                   "DRAIN"), "w"):
                pass
            self._emit(victim, "drain_requested",
                       reason="scale-down: fleet projection under the "
                              "low-water mark with an idle worker")
        return decision

    def _spawn_worker(self) -> Optional[str]:
        """Scale up: launch a worker process from the --worker-cmd
        template ({name} and {fleet} substitute). Fresh names only —
        reusing a dead worker's directory would resurrect its stale
        state."""
        if self.worker_cmd is None:
            return None
        # genuinely fresh names: skip ordinals whose row, service dir,
        # or live spawned process already exists (operators launch
        # w0/w1/... by hand — colliding would double-run one spool)
        while True:
            wid = f"w{self._next_ordinal}"
            self._next_ordinal += 1
            if wid in self._spawned \
                    or self.table.read(wid) is not None \
                    or os.path.isdir(self.table.worker_dir(wid)):
                continue
            break
        argv = [a.format(name=wid, fleet=self.dir)
                for a in shlex.split(self.worker_cmd)]
        logs = os.path.join(self.dir, "logs")
        os.makedirs(logs, exist_ok=True)
        log = open(os.path.join(logs, f"{wid}.log"), "ab")
        self._spawned[wid] = subprocess.Popen(
            argv, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        log.close()
        self._emit(wid, "spawned",
                   reason="scale-up: fleet projection over the target "
                          "window")
        return wid

    # ------------------------------------------------------------------
    # watchtower: per-beat rollup + alert rules (ISSUE 16)

    def _scrape_worker(self, wid: str) -> Optional[dict]:
        """One `metrics` scrape of a worker's service front door:
        parsed exposition samples, or None when the socket is down
        (the heartbeat-row snapshot is the fallback).

        Failures are STICKY per worker: consecutive failed scrapes
        count into `self._scrape_failures` (exported per-worker as
        `rram_scrape_failures` and fleet-wide as the
        `scrape_failures_max` observation the alert rule watches) and
        push the next attempt out by a capped exponential backoff, so
        a wedged socket costs one connect per backoff window instead
        of one per beat. Any success clears the streak."""
        path = os.path.join(self.table.worker_dir(wid), "service.sock")
        if not self.scrape_sockets or not os.path.exists(path):
            return None
        if self._beats < self._scrape_retry_beat.get(wid, 0):
            return None                      # still backing off
        if self.chaos is not None and self.chaos.socket_fault:
            self._scrape_failed(
                wid, f"chaos socket_{self.chaos.socket_fault}")
            return None
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(1.0)
        try:
            sock.connect(path)
            sock.sendall(b'{"op": "metrics"}\n')
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(1 << 20)
                if not chunk:
                    break
                buf += chunk
            resp = json.loads(buf.decode())
        except (OSError, ValueError) as e:
            self._scrape_failed(wid, str(e) or type(e).__name__)
            return None
        finally:
            sock.close()
        if not resp.get("ok") or "exposition" not in resp:
            self._scrape_failed(wid, "bad metrics response")
            return None
        from ...observe.metrics_registry import parse_exposition
        try:
            samples = parse_exposition(resp["exposition"])
        except ValueError as e:
            self._scrape_failed(wid, f"bad exposition: {e}")
            return None
        self._scrape_failures.pop(wid, None)
        self._scrape_retry_beat.pop(wid, None)
        return samples

    def _scrape_failed(self, wid: str, reason: str):
        """Bump the worker's consecutive-failure streak and schedule
        the next attempt: capped exponential backoff (1, 2, 4, 8, 8...
        beats) plus a deterministic 0/1-beat jitter (crc32, NOT hash()
        — that one is salted per process) so a fleet-wide outage's
        retries don't all land on the same beat."""
        n = self._scrape_failures.get(wid, 0) + 1
        self._scrape_failures[wid] = n
        backoff = min(1 << min(n - 1, 3), _SCRAPE_BACKOFF_CAP)
        jitter = zlib.crc32(f"{wid}:{n}".encode()) % 2
        self._scrape_retry_beat[wid] = self._beats + backoff + jitter
        if n == 1 or n % 5 == 0:
            print(f"Fleet controller: scrape of {wid} failed "
                  f"({reason}); streak {n}, retrying in "
                  f"{backoff + jitter} beat(s)", flush=True)

    def _worker_view(self, wid: str, row: dict) -> dict:
        """A uniform per-worker health view: from a live socket scrape
        when possible, else from the heartbeat row's stats snapshot
        (satellite: the table alone is enough to run the rollup)."""
        scraped = self._scrape_worker(wid)
        if scraped is not None:
            requests = {}
            for (name, labels), value in scraped.items():
                if name == "rram_requests":
                    status = dict(labels).get("status", "")
                    requests[status] = int(value)
            tot = (("tenant", "_total"),)
            view = {
                "source": "socket",
                "occupancy": scraped.get(("rram_occupancy_ratio", ()),
                                         0.0),
                "slo_burn": scraped.get(("rram_slo_burn_rate", tot),
                                        0.0),
                "projection_bias": scraped.get(
                    ("rram_projection_bias", tot), 0.0),
                "requests": requests,
                "active_requests": int(requests.get("running", 0)
                                       + requests.get("admitted", 0)),
                "projected_s": scraped.get(
                    ("rram_projected_backlog_seconds", ()), 0.0),
            }
            # crossbar health plane: present only once the worker's
            # wear ledger has censuses (registry_from_stats exports
            # the gauges conditionally, mirroring stats()["health"])
            if ("rram_health_censuses", ()) in scraped:
                view["health"] = {
                    "censuses": scraped.get(
                        ("rram_health_censuses", ()), 0),
                    "broken_frac_max": scraped.get(
                        ("rram_health_broken_frac_max", ())),
                    "wear_rate_max": scraped.get(
                        ("rram_health_wear_rate_max", ())),
                    "rul_iters_min": scraped.get(
                        ("rram_health_rul_iters_min", ())),
                    "tiles": scraped.get(("rram_health_tiles", ()), 0),
                }
            return view
        snap = row.get("stats") or {}
        view = {
            "source": "table",
            "occupancy": float(snap.get("occupancy") or 0.0),
            "slo_burn": float(snap.get("slo_burn") or 0.0),
            "projection_bias": float(snap.get("projection_bias")
                                     or 0.0),
            "requests": dict(snap.get("requests") or {}),
            "active_requests": int(snap.get("active_requests") or 0),
            "projected_s": float(snap.get("projected_s") or 0.0),
        }
        if isinstance(snap.get("health"), dict):
            view["health"] = dict(snap["health"])
        return view

    def _fleet_observation(self, rows: Dict[str, dict],
                           views: Dict[str, dict]) -> dict:
        """The per-beat metric dict the alert rules evaluate — the
        same values the rollup publishes as fleet-level gauges."""
        lanes = sum(int(r.get("lanes", 0)) for r in rows.values())
        occupied = sum(int(r.get("occupied_lanes", 0))
                       for r in rows.values())
        backlog = self._pending_backlog_iters + sum(
            int(r.get("pending_configs", 0)) * self.default_iters
            for r in rows.values())
        burn = max([float(v.get("slo_burn") or 0.0)
                    for v in views.values()], default=0.0)
        ema = self.scaler.projected_s if self.scaler is not None \
            else None
        # crossbar health plane: fleet-level wear signals over the
        # workers that report censuses. health_reporting_workers gates
        # the wear_cliff rule (alerts.py): with zero reporting workers
        # the wear metrics are absent, so the rule sees breach=None and
        # can neither fire nor flap on a health-disabled fleet.
        health = [v["health"] for v in views.values()
                  if isinstance(v.get("health"), dict)
                  and v["health"].get("censuses")]
        bf = [h.get("broken_frac_max") for h in health
              if isinstance(h.get("broken_frac_max"), (int, float))]
        ruls = [h.get("rul_iters_min") for h in health
                if isinstance(h.get("rul_iters_min"), (int, float))]
        obs_health = {"health_reporting_workers": float(len(health))}
        if bf:
            obs_health["health_broken_frac_max"] = float(max(bf))
        if ruls:
            obs_health["health_rul_iters_min"] = float(min(ruls))
        return {
            **obs_health,
            "workers": len(rows),
            "lanes": lanes,
            "occupied_lanes": occupied,
            "occupancy_ratio": (occupied / lanes) if lanes else 0.0,
            "backlog_iters": float(backlog),
            "backlog_ema": (float(ema) if ema is not None
                            else float(backlog)),
            "slo_burn_rate": burn,
            "worker_deaths_total": float(self._deaths_total),
            "swap_total": float(self._swap_cmds_total),
            "quarantine_total": float(self._quarantine_total),
            "poison_total": float(self._poison_total),
            "scrape_failures_max": float(
                max(self._scrape_failures.values(), default=0)),
            "pending_requests": len(self.spool.pending_ids()),
            "assigned_requests": len(self.assignments),
        }

    def _write_rollup(self, rows: Dict[str, dict],
                      views: Dict[str, dict], obs: dict):
        """Rewrite <fleet>/metrics.prom atomically with the fleet-wide
        gauges/counters, per-worker series, and active-alert gauges."""
        from ...observe.metrics_registry import MetricsRegistry
        reg = MetricsRegistry()
        reg.set("rram_fleet_beat", self._beats,
                help="controller scheduling beats")
        reg.set("rram_fleet_workers", obs["workers"],
                help="registered live workers")
        reg.set("rram_fleet_lanes", obs["lanes"],
                help="lanes across the fleet")
        reg.set("rram_fleet_occupied_lanes", obs["occupied_lanes"],
                help="occupied lanes across the fleet")
        reg.set("rram_fleet_occupancy_ratio", obs["occupancy_ratio"],
                help="occupied / total lanes this beat")
        reg.set("rram_fleet_backlog_iters", obs["backlog_iters"],
                help="unserved lane-iterations (routed + unrouted)")
        reg.set("rram_fleet_backlog_ema", obs["backlog_ema"],
                help="scaler's smoothed backlog projection (seconds "
                     "when a scaler runs, raw iters otherwise)")
        reg.set("rram_fleet_slo_burn_rate", obs["slo_burn_rate"],
                help="worst per-worker SLO burn rate")
        reg.set("rram_health_reporting_workers",
                obs.get("health_reporting_workers", 0.0),
                help="workers with wear-census telemetry this beat")
        if obs.get("health_broken_frac_max") is not None:
            reg.set("rram_health_broken_frac_max",
                    obs["health_broken_frac_max"],
                    help="fleet-worst per-tile broken-cell fraction")
        if obs.get("health_rul_iters_min") is not None:
            reg.set("rram_health_rul_iters_min",
                    obs["health_rul_iters_min"],
                    help="fleet-minimum remaining-useful-life (iters)")
        reg.set("rram_fleet_pending_requests", obs["pending_requests"],
                help="fleet-spool requests awaiting routing")
        reg.set("rram_fleet_assigned_requests",
                obs["assigned_requests"],
                help="requests routed and in flight")
        reg.set("rram_fleet_scale_decision", self._last_scale_decision,
                help="last scaler decision (+1 up / -1 down / 0)")
        reg.inc("rram_fleet_worker_deaths_total", self._deaths_total,
                help="workers reaped since fleet birth")
        reg.inc("rram_fleet_swap_commands_total", self._swap_cmds_total,
                help="hot-swap commands issued")
        reg.inc("rram_fleet_quarantine_total", self._quarantine_total,
                help="requests quarantined at the fleet door")
        reg.inc("rram_fleet_poison_total", self._poison_total,
                help="torn/unparseable spool, table, or state files "
                     "quarantined to poison/")
        reg.inc("rram_fleet_scale_events_total", self._scale_ups,
                help="scaler actions taken", direction="up")
        reg.inc("rram_fleet_scale_events_total", self._scale_downs,
                direction="down")
        if self._latencies:
            ordered = sorted(self._latencies)

            def pct(p):
                k = int(math.ceil(p * len(ordered))) - 1
                return ordered[max(0, min(len(ordered) - 1, k))]

            reg.set("rram_fleet_turnaround_seconds_count",
                    len(ordered),
                    help="harvested turnarounds in the quantile window")
            for q in (0.5, 0.9, 0.99):
                reg.set("rram_fleet_turnaround_seconds", pct(q),
                        help="request turnaround quantiles "
                             "(nearest-rank over the harvest window)",
                        quantile=f"{q:g}")
        firing = set(self.alert_engine.active())
        for rule in self.alert_engine.rules:
            reg.set("rram_alert_firing",
                    1 if rule.name in firing else 0,
                    help="1 while the alert rule fires",
                    alert=rule.name)
        now = time.time()
        for wid in sorted(rows):
            row, view = rows[wid], views[wid]
            reg.set("rram_worker_up", 1, help="worker liveness",
                    worker=wid)
            reg.set("rram_worker_heartbeat_age_seconds",
                    max(now - float(row.get("heartbeat_time", now)),
                        0.0),
                    help="seconds since the row refreshed", worker=wid)
            reg.set("rram_worker_lanes", int(row.get("lanes", 0)),
                    help="worker lane pool size", worker=wid)
            reg.set("rram_worker_occupied_lanes",
                    int(row.get("occupied_lanes", 0)),
                    help="worker lanes running a config", worker=wid)
            reg.set("rram_worker_pending_configs",
                    int(row.get("pending_configs", 0)),
                    help="configs queued on the worker", worker=wid)
            reg.set("rram_worker_steps_per_sec",
                    float(row.get("steps_per_sec", 0.0)),
                    help="worker dispatch-rate EMA", worker=wid)
            reg.inc("rram_worker_swap_total",
                    int(row.get("swap_count", 0)),
                    help="hot swaps applied by the worker", worker=wid)
            reg.set("rram_worker_occupancy_ratio",
                    float(view.get("occupancy") or 0.0),
                    help="worker exact lane-iteration occupancy",
                    worker=wid)
            reg.set("rram_worker_slo_burn",
                    float(view.get("slo_burn") or 0.0),
                    help="worker per-tenant-total SLO burn",
                    worker=wid)
            reg.set("rram_worker_active_requests",
                    int(view.get("active_requests") or 0),
                    help="admitted + running requests", worker=wid)
            reg.set("rram_scrape_failures",
                    int(self._scrape_failures.get(wid, 0)),
                    help="consecutive failed metric scrapes of the "
                         "worker's front door (0 clears on success)",
                    worker=wid)
            wh = view.get("health")
            if isinstance(wh, dict) and wh.get("censuses"):
                if wh.get("broken_frac_max") is not None:
                    reg.set("rram_worker_health_broken_frac_max",
                            float(wh["broken_frac_max"]),
                            help="worker-worst per-tile broken-cell "
                                 "fraction", worker=wid)
                if wh.get("rul_iters_min") is not None:
                    reg.set("rram_worker_health_rul_iters_min",
                            float(wh["rul_iters_min"]),
                            help="worker-minimum remaining-useful-life "
                                 "(iters)", worker=wid)
            for status, count in sorted(
                    (view.get("requests") or {}).items()):
                reg.set("rram_worker_requests", int(count),
                        help="worker requests by status", worker=wid,
                        status=str(status))
        tmp = self.rollup_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(reg.render())
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.rollup_path)

    def _watchtower(self, rows: Dict[str, dict]) -> List[str]:
        """Evaluate the alert rules on this beat's fleet observation,
        emit transition records, and rewrite the rollup."""
        for move in self.spool.drain_poisoned():
            self._poison_total += 1
            print("Fleet controller: quarantined torn spool file "
                  f"{move['request']} ({move['state']}) -> "
                  f"{move['moved_to']}: {move['reason']}", flush=True)
        views = {wid: self._worker_view(wid, row)
                 for wid, row in rows.items()}
        obs = self._fleet_observation(rows, views)
        transitions = self.alert_engine.evaluate(obs)
        if transitions:
            from ...observe import alert_line, make_alert_record
            for t in transitions:
                rec = make_alert_record(self._beats, **t)
                _append_jsonl(self.metrics_path, rec)
                print(f"Fleet watchtower: {alert_line(rec)}",
                      flush=True)
        self._write_rollup(rows, views, obs)
        return [f"{t['alert']}:{t['event']}" for t in transitions]

    # ------------------------------------------------------------------
    # the loop

    def _drain_file(self) -> bool:
        return os.path.exists(os.path.join(self.dir, "DRAIN"))

    def _fleet_idle(self, rows: Dict[str, dict]) -> bool:
        return (not self.spool.pending_ids() and not self.assignments
                and all(worker_load(r) == 0 for r in rows.values()))

    def run(self, max_beats: Optional[int] = None,
            drain_when_idle: bool = False,
            drain_timeout_s: float = 120.0) -> int:
        """Beat until drained. Exit 0 when the fleet drained idle, 75
        when assignments were still in flight (workers checkpointed
        them — restart the controller AND the same-named workers on
        the same fleet directory to resume)."""
        while True:
            summary = self.beat()
            if self._force_drain or self._drain_file() \
                    or (drain_when_idle
                        and self._fleet_idle(self.table.rows())):
                return self._drain(drain_timeout_s)
            if max_beats is not None and self._beats >= max_beats:
                return 0
            if not summary["routed"] and not summary["harvested"]:
                time.sleep(self.poll_interval_s)

    def _drain(self, timeout_s: float) -> int:
        try:
            os.remove(os.path.join(self.dir, "DRAIN"))
        except OSError:
            pass
        for wid in self.table.ids():
            with open(os.path.join(self.table.worker_dir(wid),
                                   "DRAIN"), "w"):
                pass
            self._emit(wid, "drain_requested",
                       reason="fleet drain")
        deadline = time.monotonic() + float(timeout_s)
        while self.table.ids() and time.monotonic() < deadline:
            time.sleep(self.poll_interval_s)
            self._harvest()
        self._harvest()
        self._write_state()
        in_flight = len(self.assignments)
        if in_flight:
            print(f"Fleet drained with {in_flight} request(s) in "
                  "flight (checkpointed by their workers); exit 75 — "
                  "restart the controller and the same-named workers "
                  "to resume", flush=True)
            return 75
        print("Fleet drained idle; exit 0", flush=True)
        return 0


def main(argv=None) -> int:
    """``python -m rram_caffe_simulation_tpu.serve.fleet`` — run the
    fleet controller until drained."""
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(
        prog="rram-sweep-fleet",
        description="fleet controller: one spool, N pod-backed "
                    "workers (see serve/fleet/controller.py)")
    p.add_argument("--fleet-dir", required=True,
                   help="durable fleet root: spool/, workers/, "
                        "fleet.jsonl, state.json")
    p.add_argument("--heartbeat-timeout", type=float, default=10.0,
                   help="seconds of heartbeat silence before a worker "
                        "is declared dead and its requests requeue")
    p.add_argument("--poll-interval", type=float, default=0.5)
    p.add_argument("--default-iters", type=int, default=100,
                   help="budget assumed for backlog projection when a "
                        "request carries no 'iters'")
    p.add_argument("--target-seconds", type=float, default=0.0,
                   help="projected-backlog window the scaler steers "
                        "toward; 0 disables scaling")
    p.add_argument("--min-workers", type=int, default=1)
    p.add_argument("--max-workers", type=int, default=4)
    p.add_argument("--worker-cmd", default=None,
                   help="scale-up template, e.g. \"python -m "
                        "rram_caffe_simulation_tpu.serve.fleet.worker "
                        "--fleet-dir {fleet} --name {name} --solver "
                        "s.prototxt\"")
    p.add_argument("--alert-rules", default=None,
                   help="JSON rule file overriding the built-in alert "
                        "rules (see serve/fleet/alerts.py "
                        "DEFAULT_RULES for the shape)")
    p.add_argument("--no-scrape", action="store_true",
                   help="skip per-beat worker socket scrapes; the "
                        "rollup runs from heartbeat rows alone")
    p.add_argument("--drain-when-idle", action="store_true",
                   help="drain the whole fleet once the spool is empty "
                        "and every worker is idle (batch/CI mode)")
    p.add_argument("--max-beats", type=int, default=0,
                   help="stop after N controller beats (test hook); "
                        "0 = unlimited")
    p.add_argument("--chaos-seed", type=int, default=0,
                   help="attach a seeded chaos plan (see fleet/"
                        "chaos.py): deterministic failure injection "
                        "on the beat clock; 0 disables. A "
                        "controller_kill injection exits 70 — restart "
                        "on the same fleet dir to prove recovery")
    args = p.parse_args(argv)

    scaler = None
    if args.target_seconds > 0:
        scaler = BacklogScaler(target_seconds=args.target_seconds,
                               min_workers=args.min_workers,
                               max_workers=args.max_workers)
    rules = None
    if args.alert_rules:
        from .alerts import load_rules
        rules = load_rules(args.alert_rules)
    chaos = None
    if args.chaos_seed:
        from .chaos import ChaosPlan
        chaos = ChaosPlan(args.chaos_seed)
    ctl = FleetController(
        args.fleet_dir,
        heartbeat_timeout_s=args.heartbeat_timeout,
        poll_interval_s=args.poll_interval,
        default_iters=args.default_iters,
        scaler=scaler, worker_cmd=args.worker_cmd,
        alert_rules=rules, scrape_sockets=not args.no_scrape,
        chaos=chaos)

    def _on_signal(signum, frame):
        with open(os.path.join(ctl.dir, "DRAIN"), "w"):
            pass

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(f"Fleet controller up: {ctl.dir} "
          f"({len(ctl.table.ids())} worker(s) registered)", flush=True)
    try:
        code = ctl.run(max_beats=args.max_beats or None,
                       drain_when_idle=args.drain_when_idle)
    except Exception as e:
        from .chaos import ControllerKilled
        if not isinstance(e, ControllerKilled):
            raise
        print(f"Fleet controller: {e}; exit 70 — restart on the same "
              "fleet dir to prove recovery", flush=True)
        code = 70
    sys.stdout.flush()
    return code


if __name__ == "__main__":
    import sys
    sys.exit(main())

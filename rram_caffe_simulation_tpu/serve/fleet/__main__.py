"""``python -m rram_caffe_simulation_tpu.serve.fleet`` — the fleet
controller CLI (see controller.py)."""
import sys

from .controller import main

sys.exit(main())

"""The fleet worker table — registration + heartbeats through the
fleet directory.

One JSON file per worker under ``<fleet>/workers/<wid>.json`` (atomic
temp-file + rename writes, same discipline as the spool). A row is the
worker's self-description:

- `pinned`: the compiled program set — canonical fault-process spec,
  dtype_policy ("f32" when none), net name, canonical tile-mapping
  spec, mesh descriptor — what the router matches request pins
  against;
- `heartbeat_time`: refreshed every worker tick; a row staler than the
  controller's `heartbeat_timeout_s` declares the worker dead and its
  in-flight requests requeue onto survivors (the at-least-once
  completion contract, lifted one level);
- load (`occupied_lanes`, `pending_configs`, `steps_per_sec`): what
  the router's least-loaded choice and the scaler's projected-backlog
  arithmetic read;
- `stats`: the watchtower snapshot (backlog projection, exact
  occupancy ratio, per-status request counts, active requests, SLO
  burn / projection bias) refreshed with every heartbeat — enough for
  `ServeClient stats` and the controller's ``metrics.prom`` rollup to
  run SOCKET-FREE from the table alone (a down front door degrades
  the plane to heartbeat granularity, never to blindness);
- `pending_swap`: set while a hot-swap command is queued — the row
  matches requests against the swap TARGET pins so the stream keeps
  routing to the worker that is about to serve it.

The worker's own service directory lives NEXT to its row
(``<fleet>/workers/<wid>/``: a full SweepService dir — spool/,
requests/, metrics.jsonl). Swap commands are a sibling control file
(``<wid>.swap.json``) the worker consumes.

Dependency-free (no jax): the controller, tests, and monitoring
scripts read the table without dragging in the framework.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from ..spool import _atomic_write

#: pin keys a worker row's `pinned` dict carries (and a request may
#: name); "mesh" is registered for operators but never matched — any
#: worker topology serves any request (SNIPPETS.md [2]'s "same code
#: from 8 chips to 6000")
PIN_KEYS = ("process", "dtype_policy", "net", "tiles")


class WorkerTable:
    """Filesystem view of ``<fleet>/workers/``.

    With `poison_dir` set (the controller's handle), an unparseable
    row file — never a half-finished write, since rows are written
    atomically — is moved aside instead of silently vanishing the
    worker: the move lands in `self.poisoned` so the controller can
    treat the worker as dead LOUDLY (requeue + alert) rather than
    leaving its in-flight requests orphaned behind an invisible row."""

    def __init__(self, fleet_dir: str,
                 poison_dir: Optional[str] = None):
        self.fleet_dir = os.path.abspath(fleet_dir)
        self.root = os.path.join(self.fleet_dir, "workers")
        self.poison_dir = poison_dir
        #: poison moves since the last `drain_poisoned()`:
        #: {"worker", "moved_to", "reason"} dicts
        self.poisoned: list = []
        os.makedirs(self.root, exist_ok=True)
        if poison_dir:
            os.makedirs(poison_dir, exist_ok=True)

    def _row_path(self, wid: str) -> str:
        return os.path.join(self.root, f"{wid}.json")

    def worker_dir(self, wid: str) -> str:
        """The worker's own SweepService directory."""
        return os.path.join(self.root, wid)

    def swap_path(self, wid: str) -> str:
        return os.path.join(self.root, f"{wid}.swap.json")

    # ------------------------------------------------------------------
    # worker side

    def register(self, wid: str, row: dict) -> dict:
        row = dict(row, worker=wid, registered_time=time.time(),
                   heartbeat_time=time.time())
        _atomic_write(self._row_path(wid), row)
        return row

    def heartbeat(self, wid: str, updates: Optional[dict] = None
                  ) -> Optional[dict]:
        """Refresh the row's heartbeat (+ load fields). None when the
        row is gone — the controller declared this worker dead and
        removed it; the worker should exit rather than resurrect a
        row whose requests were already requeued elsewhere."""
        row = self.read(wid)
        if row is None:
            return None
        row.update(updates or {})
        row["heartbeat_time"] = time.time()
        _atomic_write(self._row_path(wid), row)
        return row

    def unregister(self, wid: str):
        """Clean exit: the worker removes its own row (a MISSING row is
        a clean departure; a STALE row is a death)."""
        try:
            os.remove(self._row_path(wid))
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------------
    # swap commands (controller writes, worker consumes)

    def command_swap(self, wid: str, pinned: Dict[str, str]):
        _atomic_write(self.swap_path(wid),
                      {"pinned": dict(pinned), "time": time.time()})

    def read_swap(self, wid: str) -> Optional[dict]:
        try:
            with open(self.swap_path(wid)) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return None

    def clear_swap(self, wid: str):
        try:
            os.remove(self.swap_path(wid))
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------------
    # controller side

    def read(self, wid: str) -> Optional[dict]:
        try:
            with open(self._row_path(wid)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except ValueError as e:
            self._poison_row(wid, e)
            return None

    def _poison_row(self, wid: str, err: Exception):
        """Quarantine a torn row file (controller handles only). The
        caller sees None either way; with a poison dir the corrupt
        bytes are preserved for post-mortems and `self.poisoned`
        carries the event so the worker's death is loud, not a silent
        table vanishing."""
        if not self.poison_dir:
            return
        src = self._row_path(wid)
        dst = os.path.join(self.poison_dir, f"workers-{wid}.json")
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(self.poison_dir,
                               f"workers-{wid}.json.{n}")
        try:
            os.replace(src, dst)
        except OSError:
            return
        self.poisoned.append({"worker": wid, "moved_to": dst,
                              "reason": str(err)})

    def drain_poisoned(self) -> list:
        """Poison moves since the last drain (and clear the list)."""
        out, self.poisoned = self.poisoned, []
        return out

    def rows(self) -> Dict[str, dict]:
        """Every registered worker row, keyed by worker id."""
        out = {}
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".json") or name.endswith(".swap.json"):
                continue
            wid = name[:-len(".json")]
            row = self.read(wid)
            if row is not None:
                out[wid] = row
        return out

    def ids(self) -> List[str]:
        return sorted(self.rows())

    def remove(self, wid: str):
        """Controller-side removal of a dead worker's row (its service
        directory is left on disk for post-mortems)."""
        self.unregister(wid)
        self.clear_swap(wid)

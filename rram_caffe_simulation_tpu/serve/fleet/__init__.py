"""Fleet service (ROADMAP item 2): one durable spool, N pod-backed
workers, pinned-program routing and hot swap.

- `FleetController` (controller.py): the scheduling head — routes the
  shared spool's requests to matching warm workers, hot-swaps a
  victim's compiled program set when nothing matches, requeues a dead
  worker's in-flight requests (at-least-once), and scales the worker
  count on the projected-backlog EMA.
- `FleetWorker` (worker.py): one pod-backed `SweepService` wrapped
  with registration, heartbeats, and the hot-swap machinery (swap =
  re-place state + compile-cache hit).
- `WorkerTable` (table.py): registration + heartbeats through the
  fleet directory; dependency-free.
- `router` / `scaler`: the pure host-side decision logic — pin
  matching, least-loaded choice, swap-victim selection, backlog-EMA
  scale decisions — unit-testable without devices
  (tests/test_fleet.py); `scripts/check_fleet.py` is the CI guard for
  the whole subsystem (mixed-physics byte-identity, SIGKILL requeue,
  cache-hit swaps, fleet occupancy).
- `alerts` (alerts.py): the watchtower's declarative alert rules with
  firing/resolved hysteresis; the controller evaluates them each beat
  against the fleet rollup it writes to ``<fleet>/metrics.prom``, and
  ``caffe fleet top`` (top.py) renders the live view —
  `scripts/check_fleet_load.py` is the CI guard (load replay, alert
  lifecycle, rollup parse, byte-identity under monitoring).
- `ChaosPlan` (chaos.py): a seeded, reproducible failure-injection
  schedule on the controller's beat clock — worker SIGKILL, mid-beat
  controller kills (including a commit record torn at a seeded byte
  offset), torn spool/table writes, dropped/timed-out scrapes,
  stalled heartbeats — each applied injection a schema-validated
  ``chaos`` record; `scripts/check_fleet_chaos.py` is the CI guard
  (exactly-once terminal records and byte-identical results under
  chaos, across multiple seeds).

Run the controller with ``python -m rram_caffe_simulation_tpu.serve.fleet``
and workers with ``python -m rram_caffe_simulation_tpu.serve.fleet.worker``.
"""
from .alerts import AlertEngine, AlertRule, default_rules
from .chaos import KILL_STAGES, ChaosPlan, ControllerKilled
from .router import (effective_pins, pick_swap_victim, pick_worker,
                     request_pins, requeue_plan, route, swap_target,
                     worker_load, worker_matches)
from .scaler import BacklogScaler
from .table import PIN_KEYS, WorkerTable

__all__ = [
    "FleetController", "FleetWorker", "WorkerTable", "BacklogScaler",
    "AlertEngine", "AlertRule", "default_rules",
    "ChaosPlan", "ControllerKilled", "KILL_STAGES",
    "PIN_KEYS", "request_pins", "effective_pins", "worker_matches",
    "worker_load", "pick_worker", "pick_swap_victim", "swap_target",
    "route", "requeue_plan",
]


def __getattr__(name):
    # lazy like serve/__init__: the pure router/scaler/table layer
    # must import without the framework; controller pulls in observe,
    # worker pulls in the whole service stack
    if name == "FleetController":
        from .controller import FleetController
        return FleetController
    if name == "FleetWorker":
        from .worker import FleetWorker
        return FleetWorker
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

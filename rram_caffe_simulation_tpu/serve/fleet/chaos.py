"""Deterministic chaos-injection plane for the sweep fleet (ISSUE 20).

The paper's thesis is that hardware faults are inevitable and must be
survived by design; the fleet that simulates those faults at scale
deserves the same treatment. A `ChaosPlan` is a SEEDED, reproducible
schedule of failure injections on the controller's beat clock:

==================  =================================================
injection           what it does / what it exercises
==================  =================================================
``worker_kill``     SIGKILL a live same-host worker pid — dead-worker
                    detection, at-least-once requeue, exactly-once
                    harvest dedup across the retry
``controller_kill`` raise `ControllerKilled` at a seeded beat STAGE
                    (after reap / harvest / mid-route between a claim
                    and its worker copy / at the state.json commit,
                    torn at a seeded byte offset) — the harness cold-
                    restarts the controller on the same fleet dir and
                    the journaled beat must recover with no lost,
                    orphaned, or double-routed request
``torn_write``      write truncated JSON bytes directly into the
                    fleet spool's pending/ or the worker table — the
                    poison-quarantine path (`<fleet>/poison/`)
``socket_drop``     fail every worker scrape this beat as a refused
                    connection — scrape failure counters + backoff +
                    the `scrape_failures` alert rule
``socket_timeout``  same, surfaced as a timeout
``heartbeat_stall`` backdate a worker row's heartbeat — the stale-
                    heartbeat reap arm and the live-pid 10x grace
==================  =================================================

Every applied injection lands as a schema-validated ``chaos`` record
(observe/schema.py CHAOS_FIELDS) on ``<fleet>/fleet.jsonl``, so a
trace shows exactly what was done to the fleet next to the `worker`
and `alert` records showing how it survived.

The plan keeps its OWN monotonic beat clock (`tick`), so the schedule
is immune to controller restarts — a controller killed at plan-beat 7
resumes the same schedule at plan-beat 8 when its replacement starts
beating. Same seed, same knobs => byte-identical schedule: the guard
(`scripts/check_fleet_chaos.py`) replays failures across >= 3 seeds.

Dependency-free like router/table/alerts (no jax; the observe record
builder is imported lazily), so tests drive it without the framework.
"""
from __future__ import annotations

import json
import os
import random
import signal
import time
from typing import List, Optional

from ..spool import _atomic_write

#: beat stages a controller_kill can strike at (checkpoint() names)
KILL_STAGES = ("reap", "harvest", "claim", "route", "commit")


class ControllerKilled(Exception):
    """Raised mid-beat by an armed controller_kill injection. The
    harness treats it as the SIGKILL it simulates: discard the
    controller object and cold-restart one on the same fleet dir."""

    def __init__(self, stage: str, offset: Optional[int] = None):
        self.stage = stage
        self.offset = offset
        msg = f"chaos: controller killed at stage {stage!r}"
        if offset is not None:
            msg += f" (commit torn at byte {offset})"
        super().__init__(msg)


def _append_jsonl(path: str, rec: dict):
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


class ChaosPlan:
    """One seeded, reproducible chaos schedule (see module docstring).

    Attach to a controller with ``FleetController(..., chaos=plan)``;
    the controller calls `begin_beat` first thing every beat and
    `maybe_kill(stage)` at its transaction checkpoints. The plan
    object must outlive controller restarts (the harness holds it) —
    its beat clock and remaining schedule carry across."""

    def __init__(self, seed: int, *,
                 horizon_beats: int = 32,
                 start_beat: int = 2,
                 worker_kills: int = 1,
                 controller_kills: int = 1,
                 torn_writes: int = 2,
                 socket_drops: int = 2,
                 heartbeat_stalls: int = 1,
                 stall_s: float = 30.0,
                 kill_stages: tuple = KILL_STAGES):
        if int(horizon_beats) <= int(start_beat):
            raise ValueError("horizon_beats must exceed start_beat")
        self.seed = int(seed)
        self.stall_s = float(stall_s)
        rng = random.Random(self.seed)

        def beats(n):
            return [rng.randrange(int(start_beat),
                                  int(horizon_beats)) for _ in range(n)]

        events: List[dict] = []
        for b in beats(worker_kills):
            events.append({"beat": b, "event": "worker_kill",
                           "pick": rng.randrange(1 << 30)})
        for b in beats(controller_kills):
            events.append({"beat": b, "event": "controller_kill",
                           "stage": rng.choice(list(kill_stages)),
                           "offset": rng.randrange(4096)})
        for b in beats(torn_writes):
            events.append({"beat": b, "event": "torn_write",
                           "offset": rng.randrange(8, 160),
                           "pick": rng.randrange(1 << 30)})
        for b in beats(socket_drops):
            events.append({"beat": b,
                           "event": rng.choice(["socket_drop",
                                                "socket_timeout"])})
        for b in beats(heartbeat_stalls):
            events.append({"beat": b, "event": "heartbeat_stall",
                           "pick": rng.randrange(1 << 30)})
        events.sort(key=lambda e: (e["beat"], e["event"]))
        #: the full generated schedule (introspection / guard asserts)
        self.schedule: List[dict] = [dict(e) for e in events]
        self._pending: List[dict] = events
        self.beat = 0                  # the plan's own monotonic clock
        self._armed_kill: Optional[dict] = None
        self._socket_fault: Optional[str] = None
        self._metrics_path: Optional[str] = None
        #: applied injections, as the emitted chaos records
        self.applied: List[dict] = []

    # ------------------------------------------------------------------
    # record plumbing

    def _emit(self, event: str, **kw):
        from ...observe import make_chaos_record
        kw = {k: v for k, v in kw.items() if v is not None}
        rec = make_chaos_record(self.beat, event, seed=self.seed, **kw)
        self.applied.append(rec)
        if self._metrics_path:
            try:
                _append_jsonl(self._metrics_path, rec)
            except OSError:
                pass
        return rec

    # ------------------------------------------------------------------
    # the controller-facing surface

    def tick(self) -> int:
        self.beat += 1
        return self.beat

    @property
    def socket_fault(self) -> Optional[str]:
        """"drop" / "timeout" while a socket injection covers this
        beat — `_scrape_worker` consults it instead of the socket."""
        return self._socket_fault

    def begin_beat(self, controller) -> List[dict]:
        """Advance the plan clock and apply every injection due at
        this plan beat. Returns the chaos records emitted. A due
        controller_kill only ARMS here — it fires at its stage via
        `maybe_kill` so the strike lands mid-transaction."""
        self.tick()
        self._metrics_path = controller.metrics_path
        self._socket_fault = None
        applied = []
        while self._pending and self._pending[0]["beat"] <= self.beat:
            ev = self._pending.pop(0)
            kind = ev["event"]
            if kind == "worker_kill":
                applied.append(self._kill_worker(controller, ev))
            elif kind == "controller_kill":
                if self._armed_kill is None:
                    self._armed_kill = ev
                else:           # one armed kill at a time; defer
                    ev["beat"] = self.beat + 1
                    self._pending.insert(0, ev)
                    break
            elif kind == "torn_write":
                applied.append(self._torn_write(controller, ev))
            elif kind in ("socket_drop", "socket_timeout"):
                self._socket_fault = ("drop" if kind == "socket_drop"
                                      else "timeout")
                applied.append(self._emit(
                    kind, reason="worker metric scrapes fail this "
                                 "beat"))
            elif kind == "heartbeat_stall":
                applied.append(self._stall_heartbeat(controller, ev))
        return [a for a in applied if a is not None]

    def maybe_kill(self, stage: str):
        """Controller checkpoint: raise `ControllerKilled` when an
        armed kill names this stage. Stage "commit" is handled by
        `tear_commit` instead (the kill tears the commit record). An
        armed "claim" kill whose beat routed NOTHING (the claim
        checkpoint is per-request) degrades to the end-of-route
        checkpoint, so every scheduled kill applies deterministically
        instead of hanging armed forever on an idle fleet."""
        armed = self._armed_kill
        if armed is None or armed["stage"] == "commit":
            return
        if armed["stage"] != stage \
                and not (stage == "route" and armed["stage"] == "claim"):
            return
        self._armed_kill = None
        self._emit("controller_kill", stage=stage,
                   reason="SIGKILL mid-beat; cold restart must "
                          "recover with no lost or duplicated request")
        raise ControllerKilled(stage)

    def tear_commit(self, state_path: str, payload: dict):
        """Stage-"commit" kill: the simulated SIGKILL lands mid-write
        of state.json, so the commit record is left TORN at the seeded
        byte offset (written directly, not via the atomic tempfile —
        that is the point), then the controller dies. Restart must
        quarantine the torn record and rebuild from the spool."""
        armed = self._armed_kill
        if armed is None or armed["stage"] != "commit":
            return
        self._armed_kill = None
        blob = json.dumps(payload, indent=2).encode()
        offset = armed["offset"] % max(1, len(blob))
        with open(state_path, "wb") as f:
            f.write(blob[:offset])
        self._emit("controller_kill", stage="commit", offset=offset,
                   target=state_path,
                   reason="SIGKILL mid-write of the state.json commit "
                          "record; the torn file must quarantine on "
                          "restart")
        raise ControllerKilled("commit", offset)

    # ------------------------------------------------------------------
    # individual injections

    def _kill_worker(self, controller, ev) -> Optional[dict]:
        rows = controller.table.rows()
        victims = sorted(
            wid for wid, row in rows.items()
            if row.get("pid") and row.get("host") == _hostname())
        if not victims:
            return None
        wid = victims[ev["pick"] % len(victims)]
        pid = int(rows[wid]["pid"])
        try:
            os.kill(pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            return None
        return self._emit("worker_kill", target=wid,
                          reason=f"SIGKILL pid {pid}; in-flight "
                                 "requests must requeue exactly once")

    def _torn_write(self, controller, ev) -> dict:
        """Drop truncated JSON bytes under a live consumer directory —
        alternating between the fleet spool's pending/ and the worker
        table — exercising the poison quarantine instead of a beat
        crash."""
        junk = json.dumps({"id": f"chaos-torn-{self.beat}",
                           "tenant": "chaos",
                           "configs": [{"mean": 500.0, "std": 100.0}],
                           "iters": 10_000_000,
                           "submit_time": time.time()}, indent=2)
        blob = junk.encode()[:max(1, ev["offset"] % 160)]
        if ev["pick"] % 2 == 0:
            path = os.path.join(controller.spool.root, "pending",
                                f"zz-chaos-torn-{self.beat}.json")
        else:
            path = os.path.join(controller.table.root,
                                f"chaos-ghost-{self.beat}.json")
        with open(path, "wb") as f:
            f.write(blob)
        return self._emit("torn_write", target=path,
                          offset=len(blob),
                          reason="truncated JSON dropped under a live "
                                 "consumer dir; must quarantine to "
                                 "poison/, not crash the beat")

    def _stall_heartbeat(self, controller, ev) -> Optional[dict]:
        rows = controller.table.rows()
        if not rows:
            return None
        wids = sorted(rows)
        wid = wids[ev["pick"] % len(wids)]
        row = dict(rows[wid])
        row["heartbeat_time"] = (float(row.get("heartbeat_time",
                                               time.time()))
                                 - self.stall_s)
        _atomic_write(controller.table._row_path(wid), row)
        beats = max(1, int(self.stall_s
                           / max(controller.poll_interval_s, 1e-9))
                    if controller.poll_interval_s else 1)
        return self._emit("heartbeat_stall", target=wid,
                          beats=min(beats, 1_000_000),
                          reason=f"heartbeat backdated {self.stall_s:g}"
                                 " s; a live pid gets the 10x grace, a"
                                 " dead one reaps")

    # ------------------------------------------------------------------

    def summary(self) -> dict:
        """Counts by kind: scheduled vs applied (guard asserts)."""
        sched: dict = {}
        for e in self.schedule:
            sched[e["event"]] = sched.get(e["event"], 0) + 1
        done: dict = {}
        for r in self.applied:
            done[r["event"]] = done.get(r["event"], 0) + 1
        return {"seed": self.seed, "scheduled": sched,
                "applied": done,
                "pending": len(self._pending),
                "beat": self.beat}


def _hostname() -> str:
    import socket
    return socket.gethostname()


__all__ = ["ChaosPlan", "ControllerKilled", "KILL_STAGES"]

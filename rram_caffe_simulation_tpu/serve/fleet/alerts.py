"""Declarative alert rules with firing/resolved hysteresis.

Pure data + arithmetic, no framework imports (like ``router``/
``scaler``/``table`` in this package), so the rule engine is
unit-testable without jax and loadable by the dependency-free check
scripts.

A rule watches ONE metric in the fleet observation dict the controller
assembles each beat (the same values it writes to ``fleet/
metrics.prom``).  Three comparators cover the watchtower's needs:

- ``">"`` / ``"<"``   — level rules (SLO burn above 1, occupancy below
  the floor);
- ``"delta>"``        — growth rules on monotonic counters or EMAs
  (backlog EMA growing beat over beat, worker-death / swap / quarantine
  counters ticking up: a per-beat increase above the threshold breaches).

Hysteresis is symmetric and beat-counted: a rule FIRES only after the
condition holds for ``for_beats`` consecutive beats, and RESOLVES only
after it stays clear for ``clear_beats`` consecutive beats.  A single
clear beat resets the firing counter (and vice versa), so a metric
flapping across the threshold every beat produces **no** transitions at
all — the no-flapping property the tests pin.

The engine reports only TRANSITIONS; the controller turns each into a
schema-validated ``alert`` record and mirrors active alerts into the
rollup as ``rram_alert_firing`` gauges.
"""

from __future__ import annotations

import json

ALERT_OPS = (">", "<", "delta>")

#: Default watchtower rules.  `metric` names a key of the controller's
#: per-beat fleet observation dict (which mirrors the rollup gauges).
DEFAULT_RULES = (
    {"name": "slo_burn", "metric": "slo_burn_rate", "op": ">",
     "threshold": 1.0, "for_beats": 3, "clear_beats": 3,
     "severity": "page",
     "help": "fleet-wide mean turnaround exceeds the SLO objective"},
    {"name": "occupancy_floor", "metric": "occupancy_ratio", "op": "<",
     "threshold": 0.5, "for_beats": 5, "clear_beats": 3,
     "severity": "warn", "when_metric": "backlog_iters",
     "when_above": 0.0,
     "help": "lanes idle while a backlog is waiting"},
    {"name": "backlog_growth", "metric": "backlog_ema", "op": "delta>",
     "threshold": 0.0, "for_beats": 5, "clear_beats": 3,
     "severity": "warn",
     "help": "projected backlog EMA growing beat over beat"},
    {"name": "worker_death", "metric": "worker_deaths_total",
     "op": "delta>", "threshold": 0.0, "for_beats": 1, "clear_beats": 5,
     "severity": "page",
     "help": "a worker was reaped after missed heartbeats"},
    {"name": "swap_storm", "metric": "swap_total", "op": "delta>",
     "threshold": 0.0, "for_beats": 3, "clear_beats": 3,
     "severity": "warn",
     "help": "program hot-swaps on consecutive beats (pin thrash)"},
    {"name": "quarantine_rate", "metric": "quarantine_total",
     "op": "delta>", "threshold": 0.0, "for_beats": 2, "clear_beats": 5,
     "severity": "page",
     "help": "configs being quarantined beat over beat"},
    # crossbar health plane (observe/health.py): fires when any
    # worker's worst tile crosses the RUL projection threshold —
    # accuracy falls off the cliff once remap spares run out. Gated on
    # health_reporting_workers so a fleet with wear telemetry off (the
    # metric absent or 0) can neither fire nor flap.
    {"name": "wear_cliff", "metric": "health_broken_frac_max",
     "op": ">", "threshold": 0.3, "for_beats": 2, "clear_beats": 2,
     "severity": "page", "when_metric": "health_reporting_workers",
     "when_above": 0.0,
     "help": "a crossbar tile's broken-cell fraction crossed the "
             "remap-spare cliff"},
    # chaos / exactly-once hardening (ISSUE 20): scrape_failures_max
    # is the WORST per-worker consecutive-failure streak — transient
    # blips (streak 1-2) ride through the retry/backoff without
    # paging anyone, a wedged socket (streak 3+) fires after two
    # beats and clears two beats after the first successful scrape
    {"name": "scrape_failures", "metric": "scrape_failures_max",
     "op": ">", "threshold": 2.0, "for_beats": 2, "clear_beats": 2,
     "severity": "warn",
     "help": "a worker's metrics socket has failed several "
             "consecutive scrapes (backoff active; rollup degraded "
             "to heartbeat rows for that worker)"},
    {"name": "poison_quarantine", "metric": "poison_total",
     "op": "delta>", "threshold": 0.0, "for_beats": 1,
     "clear_beats": 3, "severity": "warn",
     "help": "torn/unparseable spool, worker-table, or state files "
             "were quarantined to <fleet>/poison/ this beat"},
)


class AlertRule:
    """One declarative rule: metric, comparator, threshold, hysteresis."""

    __slots__ = ("name", "metric", "op", "threshold", "for_beats",
                 "clear_beats", "severity", "help", "when_metric",
                 "when_above")

    def __init__(self, name, metric, op, threshold, for_beats=3,
                 clear_beats=3, severity="warn", help="",
                 when_metric=None, when_above=0.0):
        if op not in ALERT_OPS:
            raise ValueError(f"rule {name!r}: unknown op {op!r} "
                             f"(expected one of {ALERT_OPS})")
        if int(for_beats) < 1 or int(clear_beats) < 1:
            raise ValueError(f"rule {name!r}: hysteresis must be >= 1 beat")
        self.name = str(name)
        self.metric = str(metric)
        self.op = op
        self.threshold = float(threshold)
        self.for_beats = int(for_beats)
        self.clear_beats = int(clear_beats)
        self.severity = str(severity)
        self.help = str(help)
        self.when_metric = when_metric
        self.when_above = float(when_above)

    @classmethod
    def from_dict(cls, spec):
        known = {k: spec[k] for k in
                 ("name", "metric", "op", "threshold", "for_beats",
                  "clear_beats", "severity", "help", "when_metric",
                  "when_above") if k in spec}
        return cls(**known)

    def breaches(self, value, prev):
        """Does `value` breach this rule?  `prev` is the last observation
        (for delta rules); returns None when undecidable this beat."""
        if value is None:
            return None
        if self.op == ">":
            return float(value) > self.threshold
        if self.op == "<":
            return float(value) < self.threshold
        if prev is None:
            return None
        return (float(value) - float(prev)) > self.threshold


def default_rules(occupancy_floor=None, slo_burn_limit=None):
    """The built-in rule set, optionally re-thresholded."""
    rules = []
    for spec in DEFAULT_RULES:
        spec = dict(spec)
        if occupancy_floor is not None \
                and spec["name"] == "occupancy_floor":
            spec["threshold"] = float(occupancy_floor)
        if slo_burn_limit is not None and spec["name"] == "slo_burn":
            spec["threshold"] = float(slo_burn_limit)
        rules.append(AlertRule.from_dict(spec))
    return rules


def load_rules(path):
    """Load a JSON rule file: a list of rule dicts (see DEFAULT_RULES)."""
    with open(path, "r", encoding="utf-8") as fh:
        specs = json.load(fh)
    if not isinstance(specs, list):
        raise ValueError(f"{path}: rule file must be a JSON list")
    return [AlertRule.from_dict(s) for s in specs]


class AlertEngine:
    """Evaluates rules against per-beat observations, tracking state."""

    def __init__(self, rules=None):
        self.rules = list(rules) if rules is not None else default_rules()
        # name -> {"firing": bool, "breach": n, "clear": n, "prev": val}
        self._state = {r.name: {"firing": False, "breach": 0, "clear": 0,
                                "prev": None} for r in self.rules}

    def active(self):
        """Names of currently-firing rules (sorted)."""
        return sorted(n for n, s in self._state.items() if s["firing"])

    def evaluate(self, obs):
        """Fold one beat's observation dict; return transition dicts.

        Each transition is ``{"alert", "event", "metric", "value",
        "threshold", "for_beats", "severity", "reason"}`` ready to feed
        ``make_alert_record``.
        """
        transitions = []
        for rule in self.rules:
            st = self._state[rule.name]
            value = obs.get(rule.metric)
            gated = False
            if rule.when_metric is not None:
                guard = obs.get(rule.when_metric)
                gated = guard is None or float(guard) <= rule.when_above
            breach = None if gated else rule.breaches(value, st["prev"])
            if value is not None:
                st["prev"] = float(value)
            if breach is None:
                # Undecidable beat (missing metric / first delta sample /
                # gated): counts neither way.
                continue
            if breach:
                st["breach"] += 1
                st["clear"] = 0
                if not st["firing"] and st["breach"] >= rule.for_beats:
                    st["firing"] = True
                    transitions.append(self._transition(
                        rule, "firing", value,
                        f"{rule.metric} {rule.op} {rule.threshold:g} "
                        f"for {st['breach']} beats"))
            else:
                st["clear"] += 1
                st["breach"] = 0
                if st["firing"] and st["clear"] >= rule.clear_beats:
                    st["firing"] = False
                    transitions.append(self._transition(
                        rule, "resolved", value,
                        f"{rule.metric} clear of {rule.threshold:g} "
                        f"for {st['clear']} beats"))
        return transitions

    @staticmethod
    def _transition(rule, event, value, reason):
        return {
            "alert": rule.name,
            "event": event,
            "metric": rule.metric,
            "value": float(value),
            "threshold": rule.threshold,
            "for_beats": rule.for_beats,
            "severity": rule.severity,
            "reason": reason,
        }


__all__ = ["AlertRule", "AlertEngine", "default_rules", "load_rules",
           "DEFAULT_RULES", "ALERT_OPS"]

"""Sweep-as-a-service (ROADMAP item 2): a resident fault-sweep server.

- `SweepService` (service.py): the long-lived server — warm
  `SweepRunner` lane pool, durable spool + Unix-socket front door,
  continuous-batching lane packing, weighted-fair multi-tenant
  scheduling, admission control, per-request metric streams, and
  graceful drain/resume through the sweep checkpoint layer.
- `Spool` (spool.py): the durable filesystem request queue
  (pending/ -> active/ -> done/ atomic-rename lifecycle).
- `ServeClient` (serve_client.py): the client library + CLI —
  submit/status/result/wait/stats/drain/tail over the socket front
  door, falling back to direct spool files when the socket is down;
  pointed at a FLEET directory it aggregates across workers.
- `fleet/` (serve.fleet): one durable spool feeding N pod-backed
  workers — pinned-program routing, hot program swap, dead-worker
  requeue, backlog-EMA scaling (ROADMAP item 2 at its designed
  scale).

Run the server with ``python -m rram_caffe_simulation_tpu.serve`` (or
``caffe serve``), the client with
``python -m rram_caffe_simulation_tpu.serve.serve_client``.
"""
from .spool import Spool, make_request_id, normalize_request

__all__ = ["SweepService", "DRAIN_EXIT", "Spool", "ServeClient",
           "make_request_id", "normalize_request"]


def __getattr__(name):
    # lazy: `python -m ...serve.serve_client` must not pre-import the
    # submodule through the package (runpy double-import warning), and
    # client-only use should not even parse service.py
    if name in ("SweepService", "DRAIN_EXIT"):
        from . import service
        return getattr(service, name)
    if name == "ServeClient":
        from .serve_client import ServeClient
        return ServeClient
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

"""Sweep-service client — library + CLI for the `serve/` front door.

`ServeClient` talks to a running `SweepService` over its local
Unix-socket front door (one JSON object per line in/out); when the
socket is absent — the service is down, draining, or started with
`--no-socket` — submission and status fall back to the DURABLE path,
the filesystem spool itself, so a request can always be handed off
(the queue outlives the server; that is the point of the spool).

Pointed at a FLEET directory (serve/fleet/ — it has a `workers/`
table) the same client submits into the shared fleet spool, `stats`
aggregates lanes/load/tenant shares across every worker, `status`
follows the request to its assigned worker, and `tail` merges the
per-worker record streams (a requeued request has one stream per
attempt). `wait` exits with a DISTINCT code per outcome — 0
completed, 1 failed, 2 rejected, and on timeout 3 preempted vs 4
still-pending — so scripts branch without parsing JSON.

Like spool.py this module is dependency-free (no jax, no framework
imports): a monitoring script or another host sharing the filesystem
can use it without dragging in the accelerator stack.

CLI (``python -m rram_caffe_simulation_tpu.serve.serve_client``)::

    serve_client --dir /runs/svc submit --mean 500 --std 100 \
        --configs 4 --iters 200 --tenant alice          # -> request id
    serve_client --dir /runs/svc status  <id>
    serve_client --dir /runs/svc wait    <id> --timeout 600
    serve_client --dir /runs/svc result  <id>           # full payload
    serve_client --dir /runs/svc tail    <id>           # follow records
    serve_client --dir /runs/svc stats
    serve_client --dir /runs/svc drain
"""
from __future__ import annotations

import json
import os
import socket as socket_mod
import time
from typing import Iterator, Optional

from .spool import Spool

#: states reported by `status()` that end a request's lifecycle
TERMINAL_STATES = ("completed", "failed", "rejected")

#: CLI `wait` exit codes — distinct per outcome so scripts can branch
#: (a failed sweep retries elsewhere, a preempted one waits for the
#: resumed service, a still-pending one extends its timeout)
WAIT_COMPLETED = 0
WAIT_FAILED = 1
WAIT_REJECTED = 2
WAIT_PREEMPTED = 3     # timed out while preempted (service drained)
WAIT_PENDING = 4       # timed out while still pending/running


def wait_exit_code(req: Optional[dict]) -> int:
    """Map a request payload to the CLI `wait` exit code. Non-terminal
    payloads map to the timeout codes (preempted vs still-pending)."""
    status = (req or {}).get("status", (req or {}).get("state"))
    if status == "completed":
        return WAIT_COMPLETED
    if status == "failed":
        return WAIT_FAILED
    if status == "rejected":
        return WAIT_REJECTED
    if status == "preempted":
        return WAIT_PREEMPTED
    return WAIT_PENDING


def is_fleet_dir(path: str) -> bool:
    """True when `path` is a fleet directory (serve/fleet/): a worker
    table lives under `workers/` — the client then aggregates across
    the workers instead of expecting one service socket."""
    return os.path.isdir(os.path.join(path, "workers"))


class ServeClient:
    """Client handle for one service directory. `socket_path` defaults
    to `<service_dir>/service.sock`; every op tries the socket first
    and falls back to the spool files (submission stays durable even
    while the service is down — it picks the request up on restart)."""

    def __init__(self, service_dir: str,
                 socket_path: Optional[str] = None,
                 timeout_s: float = 10.0):
        self.dir = os.path.abspath(service_dir)
        self.socket_path = socket_path or os.path.join(self.dir,
                                                       "service.sock")
        self.timeout_s = float(timeout_s)
        self._spool = None
        #: consecutive socket-op failure streak; sticky until a call
        #: succeeds. Feeds the retry backoff below.
        self._sock_failures = 0
        #: monotonic time before which `_call` skips the socket and
        #: goes straight to the spool fallback (capped exponential
        #: backoff, so a wedged front door costs one connect per
        #: backoff window, not one per poll)
        self._sock_retry_at = 0.0
        #: test hook (chaos/regression): while > 0, each `_call`
        #: consumes one and fails as if the socket dropped mid-read
        self._drop_socket_ops = 0

    # ------------------------------------------------------------------
    # transport

    def _sock_failed(self):
        self._sock_failures += 1
        backoff = min(0.25 * (1 << min(self._sock_failures - 1, 5)),
                      8.0)
        self._sock_retry_at = time.monotonic() + backoff

    def _call(self, msg: dict) -> Optional[dict]:
        """One socket round-trip; None when the front door is down OR
        the response was torn/dropped mid-read. Every None falls back
        to the durable spool path, so a transient socket drop degrades
        a poll instead of crashing it; a failure streak backs the next
        attempt off (capped exponential), any success clears it."""
        if not os.path.exists(self.socket_path):
            return None
        if time.monotonic() < self._sock_retry_at:
            return None                      # still backing off
        if self._drop_socket_ops > 0:
            self._drop_socket_ops -= 1
            self._sock_failed()
            return None
        sock = socket_mod.socket(socket_mod.AF_UNIX,
                                 socket_mod.SOCK_STREAM)
        sock.settimeout(self.timeout_s)
        try:
            sock.connect(self.socket_path)
            sock.sendall((json.dumps(msg) + "\n").encode())
            buf = b""
            while b"\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    # connection dropped mid-read: a torn (possibly
                    # partial) response counts as a failure too
                    raise ConnectionResetError(
                        "socket closed before a full response")
                buf += chunk
            # a torn response (the service died mid-write) must read
            # as "front door down", not crash the caller's poll loop
            resp = json.loads(buf.split(b"\n", 1)[0].decode())
        except (OSError, ValueError, socket_mod.timeout):
            self._sock_failed()
            return None
        finally:
            sock.close()
        self._sock_failures = 0
        self._sock_retry_at = 0.0
        if not resp.get("ok"):
            raise RuntimeError(
                f"service refused {msg.get('op')!r}: "
                f"{resp.get('error', 'unknown error')}")
        return resp

    def _spool_handle(self) -> Spool:
        if self._spool is None:
            self._spool = Spool(os.path.join(self.dir, "spool"))
        return self._spool

    # ------------------------------------------------------------------
    # fleet directory support (serve/fleet/): the same client against
    # a fleet root aggregates across the workers' service dirs

    def _is_fleet(self) -> bool:
        return is_fleet_dir(self.dir)

    def _table(self):
        """The fleet worker table (serve/fleet/table.py — like this
        module it is dependency-free, so the client shares its
        file-format knowledge instead of re-implementing it)."""
        from .fleet.table import WorkerTable
        return WorkerTable(self.dir)

    def _worker_ids(self):
        """Worker ids with a service directory under `workers/` —
        includes departed/dead workers (no table row), whose streams
        and spools still answer status/tail queries."""
        root = os.path.join(self.dir, "workers")
        try:
            return sorted(n for n in os.listdir(root)
                          if os.path.isdir(os.path.join(root, n)))
        except FileNotFoundError:
            return []

    def _worker_client(self, wid: str) -> "ServeClient":
        return ServeClient(self._table().worker_dir(wid),
                           timeout_s=self.timeout_s)

    def _worker_rows(self) -> dict:
        """The worker table (registration + heartbeat rows)."""
        return self._table().rows()

    # ------------------------------------------------------------------
    # ops

    def ping(self) -> bool:
        """True when the front door answers."""
        return self._call({"op": "ping"}) is not None

    def submit(self, request: dict) -> dict:
        """Submit a fault-sweep request:
        ``{"configs": [{"mean", "std"}, ...], "iters": N,
        "tenant": "...", "id": optional}``. Returns {"id", "state",
        "projected_s"?}. Socket down -> the request is spooled
        directly (durable; validated again at pickup)."""
        resp = self._call({"op": "submit", "request": request})
        if resp is not None:
            return {k: resp[k] for k in ("id", "state", "projected_s")
                    if k in resp}
        rid = self._spool_handle().submit(request)
        return {"id": rid, "state": "pending", "projected_s": None}

    def status(self, request_id: str) -> Optional[dict]:
        """The request's current payload (spool file merged with the
        service's live progress when it answers); None = unknown id.
        Against a fleet directory the fleet spool answers, enriched
        with the assigned worker's live view while the request is
        routed."""
        resp = self._call({"op": "status", "id": request_id})
        if resp is not None:
            return resp["request"]
        req = self._spool_handle().read(request_id)
        if self._is_fleet():
            if req is not None and req.get("state") == "active" \
                    and req.get("worker"):
                live = self._worker_client(req["worker"]) \
                    .status(request_id)
                if live is not None:
                    merged = dict(req)
                    merged.update(live)
                    merged["worker"] = req["worker"]
                    return merged
            elif req is None:
                # e.g. submitted straight to a worker, or a crashed
                # controller: the worker spools still answer
                for wid in self._worker_ids():
                    live = self._worker_client(wid).status(request_id)
                    if live is not None:
                        return dict(live, worker=wid)
        return req

    def result(self, request_id: str) -> Optional[dict]:
        """Alias of `status` — a terminal request's payload carries the
        per-config results."""
        return self.status(request_id)

    def stats(self) -> Optional[dict]:
        """Service-level snapshot (lanes, occupancy, projection,
        per-tenant shares); None when the service is down (the spool
        has no service-level view). Against a fleet directory the
        snapshot AGGREGATES across workers: fleet totals, per-worker
        pinned sets + live stats, per-tenant lane-iteration sums."""
        resp = self._call({"op": "stats"})
        if resp is not None:
            return resp["stats"]
        if not self._is_fleet():
            return None
        rows = self._worker_rows()
        workers = {}
        totals = {"lanes": 0, "occupied_lanes": 0,
                  "pending_configs": 0, "steps_per_sec": 0.0}
        tenant_iters = {}
        req_counts = {}
        for wid in self._worker_ids():
            row = rows.get(wid)
            entry = {"registered": row is not None}
            if row is not None:
                entry["pinned"] = row.get("pinned")
                entry["heartbeat_age_s"] = round(
                    max(time.time()
                        - float(row.get("heartbeat_time", 0)), 0.0), 2)
            ws = self._worker_client(wid).stats()
            if ws is not None:
                entry["stats"] = {k: ws.get(k) for k in
                                  ("lanes", "occupied_lanes",
                                   "pending_configs", "steps_per_sec",
                                   "projected_s", "occupancy", "slo",
                                   "iter")}
                for k in totals:
                    totals[k] += ws.get(k) or 0
                for t, v in (ws.get("tenant_lane_iters")
                             or {}).items():
                    tenant_iters[t] = tenant_iters.get(t, 0) + int(v)
                for s, n in (ws.get("requests") or {}).items():
                    req_counts[s] = req_counts.get(s, 0) + int(n)
            elif row is not None:
                # service socket down: the heartbeat row IS the stats
                # view — its load fields plus the watchtower snapshot
                # the worker publishes on every heartbeat, so fleet
                # stats stay complete socket-free (table-only mode)
                for k in totals:
                    totals[k] += row.get(k) or 0
                snap = row.get("stats") or {}
                entry["stats"] = {
                    "lanes": row.get("lanes"),
                    "occupied_lanes": row.get("occupied_lanes"),
                    "pending_configs": row.get("pending_configs"),
                    "steps_per_sec": row.get("steps_per_sec"),
                    "projected_s": snap.get("projected_s"),
                    "occupancy": snap.get("occupancy"),
                    "slo_burn": snap.get("slo_burn"),
                    "active_requests": snap.get("active_requests"),
                    "iter": snap.get("iter"),
                    "source": "heartbeat_row",
                }
                for s, n in (snap.get("requests") or {}).items():
                    req_counts[s] = req_counts.get(s, 0) + int(n)
            workers[wid] = entry
        totals["steps_per_sec"] = round(totals["steps_per_sec"], 4)
        return {
            "fleet": True,
            "workers": workers,
            "alive_workers": len(rows),
            "pending_requests":
                len(self._spool_handle().pending_ids()),
            "tenant_lane_iters": tenant_iters,
            "requests": req_counts,
            **totals,
        }

    def drain(self) -> bool:
        """Ask the service to drain gracefully. Socket down -> drop the
        durable DRAIN control file so the (re)started service drains at
        its next beat. Always succeeds."""
        if self._call({"op": "drain"}) is not None:
            return True
        with open(os.path.join(self.dir, "DRAIN"), "w"):
            pass
        return True

    def wait(self, request_id: str, timeout_s: float = 600.0,
             poll_s: float = 0.5) -> dict:
        """Block until the request reaches a terminal state; returns
        the terminal payload. TimeoutError after `timeout_s`."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            req = self.status(request_id)
            if req is not None and req.get("status",
                                           req.get("state")) \
                    in TERMINAL_STATES:
                return req
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"request {request_id} not terminal after "
                    f"{timeout_s:g} s (last: "
                    f"{(req or {}).get('status', 'unknown')})")
            time.sleep(poll_s)

    def records_path(self, request_id: str) -> str:
        """The request's own JSONL metrics stream (one schema-validated
        `request` record per lifecycle transition)."""
        return os.path.join(self.dir, "requests",
                            f"{request_id}.jsonl")

    def tail(self, request_id: str, follow: bool = True,
             poll_s: float = 0.25,
             timeout_s: Optional[float] = None) -> Iterator[dict]:
        """Yield the request's lifecycle records as they land; with
        `follow`, keeps reading until a terminal record (or
        `timeout_s`). The stream is per-request, so a tenant tails
        their own request without seeing anyone else's."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)

        def read_lines(path, start):
            """Complete ("\\n"-terminated) records past byte `start`,
            plus the offset consumed. A PARTIAL trailing line — the
            writer mid-append, or a reader racing a torn write — is
            NOT consumed: the position stays before it, so the next
            poll re-reads it whole instead of crashing on half a
            JSON object."""
            with open(path, "rb") as f:
                f.seek(start)
                data = f.read()
            recs, consumed = [], 0
            for raw in data.splitlines(keepends=True):
                if not raw.endswith(b"\n"):
                    break               # partial tail: retry next poll
                consumed += len(raw)
                line = raw.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line.decode()))
                except (ValueError, UnicodeDecodeError):
                    continue            # corrupt complete line: skip
            return recs, start + consumed

        if self._is_fleet():
            # a fleet request's stream lives with whichever worker(s)
            # served it — a requeued request has one stream per
            # attempt, so re-scan the worker set each poll and tag
            # each record with its worker. The terminal record lands
            # on the final attempt's stream only.
            pos: dict = {}
            while True:
                for wid in self._worker_ids():
                    path = os.path.join(self.dir, "workers", wid,
                                        "requests",
                                        f"{request_id}.jsonl")
                    if not os.path.exists(path):
                        continue
                    recs, pos[path] = read_lines(path,
                                                 pos.get(path, 0))
                    for rec in recs:
                        rec.setdefault("worker", wid)
                        yield rec
                        if rec.get("event") in TERMINAL_STATES:
                            return
                if not follow:
                    return
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    return
                time.sleep(poll_s)
        path = self.records_path(request_id)
        fpos = 0
        while True:
            if os.path.exists(path):
                recs, fpos = read_lines(path, fpos)
                for rec in recs:
                    yield rec
                    if rec.get("event") in TERMINAL_STATES:
                        return
            if not follow:
                return
            if deadline is not None and time.monotonic() >= deadline:
                return
            time.sleep(poll_s)


def _wait_and_report(client: ServeClient, request_id: str,
                     timeout_s: float) -> int:
    """The CLI `wait` contract: print the terminal payload and exit
    with a DISTINCT code per outcome (wait_exit_code) — 0 completed,
    1 failed, 2 rejected; on timeout, 3 while preempted (a drained
    service holds the checkpointed request) vs 4 still
    pending/running — so scripts branch without parsing JSON."""
    import sys
    try:
        req = client.wait(request_id, timeout_s=timeout_s)
    except TimeoutError:
        req = client.status(request_id) or {}
        state = req.get("status", req.get("state", "unknown"))
        print(f"timeout: request {request_id} not terminal after "
              f"{timeout_s:g} s (last: {state})", file=sys.stderr)
        return wait_exit_code(req)
    print(json.dumps(req, indent=2))
    return wait_exit_code(req)


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="rram-sweep-client",
        description="client for the resident sweep service (serve/)")
    p.add_argument("--dir", required=True,
                   help="the service's --service-dir")
    p.add_argument("--socket", default=None,
                   help="socket path override (default "
                        "<dir>/service.sock)")
    sub = p.add_subparsers(dest="op", required=True)

    sp = sub.add_parser("submit", help="submit a fault-sweep request")
    sp.add_argument("--mean", type=float, action="append", default=[],
                    help="per-config lifetime mean (repeat per config, "
                         "or give one with --configs N)")
    sp.add_argument("--std", type=float, action="append", default=[],
                    help="per-config lifetime std (pairs with --mean)")
    sp.add_argument("--configs", type=int, default=0,
                    help="replicate a single --mean/--std into N "
                         "configs")
    sp.add_argument("--iters", type=int, default=0,
                    help="iteration budget (0 = service default)")
    sp.add_argument("--process", default=None,
                    help="fault-process pin (fleet: routes to a "
                         "matching worker or hot-swaps one; single "
                         "service: must match its compiled physics)")
    sp.add_argument("--tiles", default=None,
                    help="tile-mapping pin (same contract)")
    sp.add_argument("--dtype-policy", default=None,
                    help="quantized-mode pin ('f32'|'ternary'|'int8')")
    sp.add_argument("--net", default=None,
                    help="net-name pin (the worker-table net name)")
    sp.add_argument("--tenant", default="default")
    sp.add_argument("--id", default=None,
                    help="explicit request id (default: generated)")
    sp.add_argument("--wait", action="store_true",
                    help="block until terminal and print the result")
    sp.add_argument("--timeout", type=float, default=600.0)

    for op in ("status", "result"):
        q = sub.add_parser(op)
        q.add_argument("id")
    w = sub.add_parser("wait", help="block until a request is terminal")
    w.add_argument("id")
    w.add_argument("--timeout", type=float, default=600.0)
    t = sub.add_parser("tail", help="follow a request's record stream")
    t.add_argument("id")
    t.add_argument("--no-follow", action="store_true")
    t.add_argument("--timeout", type=float, default=None)
    sub.add_parser("stats")
    sub.add_parser("drain")
    sub.add_parser("ping")

    args = p.parse_args(argv)
    client = ServeClient(args.dir, socket_path=args.socket)

    if args.op == "ping":
        up = client.ping()
        print("up" if up else "down (spool submissions still durable)")
        return 0 if up else 1
    if args.op == "submit":
        means, stds = list(args.mean), list(args.std)
        if len(means) != len(stds):
            p.error("--mean and --std must pair up")
        if not means:
            p.error("submit needs at least one --mean/--std pair")
        if args.configs:
            if len(means) != 1:
                p.error("--configs N replicates a SINGLE --mean/--std "
                        "pair")
            means, stds = means * args.configs, stds * args.configs
        req = {"tenant": args.tenant,
               "configs": [{"mean": m, "std": s}
                           for m, s in zip(means, stds)]}
        if args.iters:
            req["iters"] = args.iters
        if args.id:
            req["id"] = args.id
        for pin in ("process", "tiles", "dtype_policy", "net"):
            val = getattr(args, pin)
            if val:
                req[pin] = val
        out = client.submit(req)
        if args.wait:
            return _wait_and_report(client, out["id"], args.timeout)
        print(json.dumps(out, indent=2))
        return 0
    if args.op in ("status", "result"):
        req = client.status(args.id)
        if req is None:
            print(f"unknown request id {args.id!r}", file=sys.stderr)
            return 1
        print(json.dumps(req, indent=2))
        return 0
    if args.op == "wait":
        return _wait_and_report(client, args.id, args.timeout)
    if args.op == "tail":
        try:
            for rec in client.tail(args.id,
                                   follow=not args.no_follow,
                                   timeout_s=args.timeout):
                print(json.dumps(rec), flush=True)
        except BrokenPipeError:
            # `tail ... | head` closed the pipe — that is the reader
            # saying "enough", not an error
            try:
                sys.stdout.close()
            except OSError:
                pass
        return 0
    if args.op == "stats":
        stats = client.stats()
        if stats is None:
            print("service down (no socket); stats need a live "
                  "service", file=sys.stderr)
            return 1
        print(json.dumps(stats, indent=2))
        return 0
    if args.op == "drain":
        client.drain()
        print("drain requested")
        return 0
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())

"""SweepService — the resident fault-sweep server (ROADMAP item 2).

The production story for "millions of users" is not a CLI that pays a
cold start per sweep: it is ONE long-lived process that holds the
compiled chunk programs, the device-resident dataset, and a warm
vectorized-lane pool, and feeds fault-sweep REQUESTS into the
self-healing lane machinery continuous-batching style — a freed lane is
re-seeded with the next queued request's configs at the very next chunk
boundary (Caffe Barista, arXiv 2006.13829, made the same move for
FPGAs inside the Caffe training loop; CIM-Explorer, arXiv 2505.14303,
is the workload shape: large batches of heterogeneous crossbar-config
evaluations whose TURNAROUND is what users feel).

Execution model
---------------
The runner runs `enable_self_healing(start_empty=True,
virtual_time=True)`: no pre-assigned resident configs, every lane idle
until a submission seeds it, and every lane on its OWN iteration clock
— so a request's results depend only on (spec, config id, attempt,
budget, solver seed), never on co-tenants, arrival time, or lane
placement. That schedule-independence is the service's reproducibility
contract: results are byte-identical to a direct `SweepRunner`
execution of the same submissions (scripts/check_serve_contract.py).

Front doors
-----------
Requests arrive over a DURABLE queue: the filesystem spool
(`<dir>/spool/pending`, one atomic JSON file per request — see
spool.py) is the source of truth, and a local Unix-socket front door
(serve_client.py is the library + CLI) is the convenience layer that
validates, spools, and answers status/result/stats queries without the
client touching the filesystem layout.

On top ride:

- **multi-tenant weighted fairness**: freed lanes are handed to the
  tenant with the smallest weight-normalized lane share at each chunk
  boundary (`tenant_weights`), with per-tenant lane-iteration
  accounting in `stats()`;
- **admission control with backpressure**: the projected backlog
  turnaround (pending + in-flight lane-iterations over the measured
  step rate) is compared against the configured SLO window
  (`slo_seconds`) — policy "reject" refuses the request with the
  projection in its terminal record, policy "queue" admits it but
  flags the risk;
- **per-request metric streams**: every lifecycle transition is a
  schema-validated `request` record (observe/schema.py), written to
  the service-wide metrics JSONL *and* the request's own
  `requests/<id>.jsonl` so a tenant can tail their request alone;
- **graceful drain**: SIGTERM (or the client's `drain` op) stops
  admission, checkpoints the in-flight lanes through the existing v3
  sweep checkpoint layer plus the request table, and exits 75
  (EX_TEMPFAIL) — a restarted service resumes with ZERO lost work and
  bit-identical results (virtual time makes the resumed trajectories
  independent of the interruption).

    python -m rram_caffe_simulation_tpu.serve \
        --solver models/cifar10_quick/cifar10_quick_lmdb_solver.prototxt \
        --service-dir /runs/sweep-svc --lanes 256 --drain-when-idle
"""
from __future__ import annotations

import json
import os
import socket as socket_mod
import threading
import time
from typing import Dict, List, Optional

from .spool import Spool, _atomic_write, normalize_request

#: exit code of a drained service with in-flight requests checkpointed
#: — EX_TEMPFAIL, the same "retry me" code the durable sweep driver
#: uses, so schedulers restart the service with the same --service-dir
#: and it resumes with zero lost work. A drain with nothing in flight
#: exits 0.
DRAIN_EXIT = 75

#: AF_UNIX sun_path is ~104 bytes on the small end; refuse politely
_MAX_SOCK_PATH = 100

_TERMINAL = ("completed", "failed", "rejected")


def _append_jsonl(path: str, rec: dict):
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


class SweepService:
    """A resident sweep server over one warm `SweepRunner` lane pool.

    `solver_param` is a solver prototxt path or SolverParameter; it
    must pin `random_seed` (the request-result contract is keyed by
    it) and configure a gaussian `failure_pattern` (per-request
    mean/std override it per config). The net must have a
    materializable Data layer — the service holds the decoded dataset
    device-resident.

    Single-threaded core: only `serve()`'s loop thread touches the
    runner. The socket front door and `submit()` write spool files;
    status/stats reads go through lock-protected snapshots.
    """

    def __init__(self, solver_param, service_dir: str, *,
                 lanes: int = 8, chunk: int = 8,
                 default_iters: int = 100, max_retries: int = 1,
                 retry_backoff: int = 0,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 slo_seconds: float = 0.0, admission: str = "queue",
                 poll_interval_s: float = 0.5,
                 pipeline_depth: int = 0,
                 socket_path: Optional[str] = "",
                 allow_inject: bool = False,
                 save_fault_results: bool = False,
                 mesh=None,
                 trace: bool = False,
                 profile_dir: Optional[str] = None,
                 fault_process=None, tile_spec=None,
                 dtype_policy=None, net_name: Optional[str] = None,
                 health_every: int = 0,
                 runner_kw: Optional[dict] = None):
        from ..observe import JsonlSink
        from ..observe.spans import OccupancyAggregator, SloAccountant
        from ..parallel import SweepRunner
        from ..solver import Solver
        from ..utils.io import read_solver_param

        if admission not in ("queue", "reject"):
            raise ValueError(f"admission policy {admission!r} must be "
                             "'queue' or 'reject'")
        if int(default_iters) <= 0:
            raise ValueError("default_iters must be > 0: it is the "
                             "budget for requests that carry no "
                             "'iters' of their own")
        self.dir = os.path.abspath(service_dir)
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(os.path.join(self.dir, "requests"), exist_ok=True)
        # the service owns its spool's consumption, so it also owns
        # the poison quarantine: an unparseable spool file moves to
        # <dir>/poison/ (surfaced via stats) instead of crash-looping
        # the beat (ISSUE 20)
        self.spool = Spool(os.path.join(self.dir, "spool"),
                           poison_dir=os.path.join(self.dir, "poison"))
        self.chunk = int(chunk)
        self.default_iters = int(default_iters)
        self.slo_seconds = float(slo_seconds)
        self.admission = admission
        self.poll_interval_s = float(poll_interval_s)
        self.allow_inject = bool(allow_inject)
        self.save_fault_results = bool(save_fault_results)
        self.tenant_weights = {str(k): float(v)
                               for k, v in (tenant_weights or {}).items()}
        self._drain_flag = threading.Event()
        self._stats_lock = threading.Lock()
        self._stats_view: dict = {}
        #: request records emitted on the socket thread, queued for
        #: the loop thread (the shared metrics sink is unlocked)
        self._front_records: List[dict] = []
        self._steps_per_sec = 0.0          # EMA of dispatch rate
        self._first_timed_beat = True      # first beat pays jit compile
        self._tenant_lane_iters: Dict[str, int] = {}
        self._requests: Dict[str, dict] = {}   # id -> table entry
        self._cfg_req: Dict[int, str] = {}     # global config id -> id
        self._closed = False
        #: fleet-worker hooks (serve/fleet/worker.py): with
        #: `pause_admission` set, the loop leaves pending spool
        #: requests untouched (a hot program swap is queued — they
        #: will be admitted by the REBUILT service, whose pins they
        #: match); `admission_gate` is the race-free version — a
        #: callable checked at EVERY admission pass (the fleet worker
        #: points it at its swap-command file, which the controller
        #: writes strictly BEFORE routing mismatched requests into
        #: this spool, so they can never be mis-admitted — and
        #: mis-REJECTED — by the pre-swap program); `drained` records
        #: that serve() returned through the drain path, so a wrapper
        #: driving serve(max_beats=...) in a loop can tell a drain
        #: from an exhausted beat budget.
        self.pause_admission = False
        self.admission_gate = None
        self.drained = False

        # the pinned program set (serve/fleet/): this service compiles
        # ONE (fault_process, dtype_policy, net, tile_spec) — requests
        # pinning anything else are refused at admission, and the fleet
        # router sends them to a matching worker (or hot-swaps one)
        # instead. `net_name` is the short name the worker table
        # registers (defaults to the solver prototxt's basename).
        param = (read_solver_param(solver_param)
                 if isinstance(solver_param, (str, os.PathLike))
                 else solver_param)
        if net_name is None and isinstance(solver_param,
                                           (str, os.PathLike)):
            net_name = os.path.splitext(
                os.path.basename(str(solver_param)))[0]
        self.net_name = str(net_name) if net_name else "default"
        if param.random_seed < 0:
            raise ValueError(
                "SweepService needs solver random_seed >= 0: request "
                "results are keyed by (spec, config id, seed), and a "
                "wall-clock seed would break resume and the "
                "reproducibility contract")
        if not (param.HasField("failure_pattern")
                and param.failure_pattern.type == "gaussian"):
            raise ValueError(
                "SweepService needs failure_pattern { type: 'gaussian' }"
                " — requests override mean/std per config")
        param.display = 0
        param.ClearField("test_interval")

        resuming = os.path.exists(self._state_path())
        self.solver = Solver(param, fault_process=fault_process,
                             tile_spec=tile_spec)
        self.solver.enable_metrics(JsonlSink(
            os.path.join(self.dir, "metrics.jsonl"), append=resuming,
            unbuffered=True))
        # `mesh` lays the lane pool's config axis over a device mesh
        # (make_mesh({"config": N}) or a parse_mesh_shape spec string):
        # the service's N warm lanes then live as ONE config-sharded
        # GSPMD program over N chips — same request/packing semantics,
        # N x the resident pool per host. virtual_time requires a
        # config-only mesh (the runner validates).
        if isinstance(mesh, str):
            from ..parallel import mesh_from_spec
            mesh = mesh_from_spec(mesh)
        runner_kw = dict(runner_kw or {})
        if dtype_policy is not None:
            runner_kw.setdefault("dtype_policy", dtype_policy)
        if health_every:
            # crossbar health plane (observe/health.py): the runner
            # censuses lane wear every `health_every` iterations;
            # stats()["health"] and the `metrics` socket op surface
            # the ledger's rollup as rram_health_* gauges
            runner_kw.setdefault("health_every", int(health_every))
        self.runner = SweepRunner(self.solver, n_configs=int(lanes),
                                  pipeline_depth=int(pipeline_depth),
                                  mesh=mesh,
                                  **runner_kw)
        self.runner.enable_self_healing(
            budget=self.default_iters, max_retries=int(max_retries),
            backoff_iters=int(retry_backoff), start_empty=True,
            virtual_time=True)
        self.runner.set_refill_policy(self._fair_order)
        self.runner.on_lane_complete = self._on_lane_complete
        self._lane_results: Dict[int, dict] = {}   # cfg -> fault rows
        # span tracing (observe/spans.py): request lifetimes as async
        # spans linked by request id, beat/admit/harvest spans on the
        # loop thread, the runner's dispatch/consume/heal spans — one
        # shared tracer, one merged timeline. Span records ride the
        # service-wide metrics stream; the Perfetto export lands under
        # `profile_dir` (default <service-dir>/trace) on close.
        self._tracer = None
        if trace:
            self._tracer = self.runner.enable_tracing(
                profile_dir=profile_dir
                or os.path.join(self.dir, "trace"))
        # utilization layer (always on — plain host arithmetic): exact
        # per-beat lane occupancy, and the SLO ledger comparing each
        # terminal request's achieved turnaround against the admission
        # controller's EMA projection (stats()["slo"])
        self._occ = OccupancyAggregator()
        self._slo = SloAccountant(self.slo_seconds)

        if resuming:
            self._resume()
        self._update_stats_view()

        self._sock_server = None
        if socket_path is not None:
            path = socket_path or os.path.join(self.dir, "service.sock")
            if len(path) > _MAX_SOCK_PATH:
                print(f"Sweep service: socket path {path!r} exceeds "
                      f"{_MAX_SOCK_PATH} chars — front door disabled, "
                      "spool submissions still work", flush=True)
            else:
                self._sock_server = _SocketServer(self, path)
                self._sock_server.start()

    # ------------------------------------------------------------------
    # front door (thread-safe: spool writes + snapshots only)

    def submit(self, request: dict) -> dict:
        """Validate + spool a request (the in-process twin of the
        socket `submit` op). Returns {"id", "state": "pending",
        "projected_s"} — the projection is advisory; the admission
        DECISION happens at pickup, where it is recorded."""
        if request.get("inject_nan") is not None \
                and not self.allow_inject:
            raise ValueError("inject_nan is a test hook; start the "
                             "service with allow_inject=True to use it")
        # submit_seen rides the INITIAL atomic write: the loop thread
        # may claim the file the instant it lands, so a follow-up
        # update of the pending/ name could race a rename
        req = normalize_request(dict(request, submit_seen=True),
                                self.default_iters)
        if self.spool.state_of(req["id"]) is not None:
            raise ValueError(f"request id {req['id']!r} already "
                             "exists in the spool")
        # the 'submitted' record lands BEFORE the spool file: the loop
        # thread may claim the file the instant it appears, and its
        # 'admitted' append to requests/<id>.jsonl must not beat
        # 'submitted' in the stream a tenant tails
        self._emit_request(req, "submitted",
                           configs=len(req["configs"]),
                           front_door=True)
        rid = self.spool.submit(req, self.default_iters)
        # advisory projection from the lock-protected snapshot (this
        # may run on the socket thread; the live healing state belongs
        # to the loop thread — the admission DECISION happens there)
        view = self.stats()
        projected = None
        rate = float(view.get("steps_per_sec") or 0.0) \
            * int(view.get("lanes") or 0)
        if rate > 0:
            projected = (float(view.get("projected_s") or 0.0)
                         + req["iters"] * len(req["configs"]) / rate)
        return {"id": rid, "state": "pending",
                "projected_s": projected}

    def status(self, request_id: str) -> Optional[dict]:
        """The request's spool payload merged with the live table
        entry (progress counts) — None when unknown."""
        req = self.spool.read(request_id)
        if req is None:
            return None
        with self._stats_lock:
            entry = self._requests.get(request_id)
            if entry is not None:
                req.update({k: entry[k] for k in
                            ("status", "done", "configs_total")
                            if k in entry})
        return req

    def stats(self) -> dict:
        """Service-level snapshot: lanes, occupancy, measured dispatch
        rate, backlog projection, per-tenant lane-share accounting."""
        with self._stats_lock:
            return dict(self._stats_view)

    def drain(self):
        """Request a graceful drain (same as SIGTERM on the CLI): the
        loop stops admitting, checkpoints in-flight lanes + the request
        table, and exits 75 (or 0 when nothing is in flight)."""
        self._drain_flag.set()

    # ------------------------------------------------------------------
    # scheduling core (loop thread only)

    def serve(self, max_beats: Optional[int] = None,
              drain_when_idle: bool = False) -> int:
        """The scheduling loop: admit pending spool requests, dispatch
        one chunk across the lane pool, harvest terminal configs, emit
        lifecycle records, repeat. Returns the process exit code: 0
        (idle drain / `max_beats` reached / `drain_when_idle` and the
        queue ran dry) or 75 (drained with in-flight work
        checkpointed)."""
        beats = 0
        while True:
            self._flush_front_records()
            self._drain_spans()
            if self._drain_flag.is_set() or self._drain_file():
                return self._drain_exit()
            t_admit = (time.perf_counter() if self._tracer is not None
                       else 0.0)
            admitted = self._admit_pending()
            if self._tracer is not None and admitted:
                self._tracer.complete(
                    "admit", time.perf_counter() - t_admit, cat="serve",
                    iteration=self.runner.iter,
                    args={"admitted": admitted})
            worked = False
            if not self.runner.healing_complete():
                self._maybe_inject()
                t0 = time.perf_counter()
                self.runner.step(self.chunk, chunk=self.chunk)
                dt = time.perf_counter() - t0
                # occupancy sampled AFTER the step: configs seeded by
                # the step's leading heal pass trained this chunk and
                # must be credited to their tenant (configs that hit
                # budget are harvested at the NEXT step's pass, so
                # they are still visible here)
                self._account_beat(self._tenant_occupancy(), dt)
                if self._tracer is not None:
                    self._tracer.complete(
                        "beat", dt, cat="serve",
                        iteration=self.runner.iter,
                        args={"beat": beats})
                worked = True
            t_harvest = (time.perf_counter() if self._tracer is not None
                         else 0.0)
            self._harvest()
            if self._tracer is not None and worked:
                self._tracer.complete(
                    "harvest", time.perf_counter() - t_harvest,
                    cat="serve", iteration=self.runner.iter)
            self._update_stats_view()
            self._write_state()
            beats += 1
            if max_beats is not None and beats >= max_beats:
                return 0
            if not worked and not admitted:
                if drain_when_idle and not self.spool.pending_ids() \
                        and not self._active_ids():
                    return self._drain_exit()
                # idle: wait for the spool, a signal, or the socket
                self._drain_flag.wait(self.poll_interval_s)

    def _active_ids(self) -> List[str]:
        return [rid for rid, e in self._requests.items()
                if e["status"] not in _TERMINAL]

    def _drain_file(self) -> bool:
        return os.path.exists(os.path.join(self.dir, "DRAIN"))

    def _process_canonical(self) -> str:
        """The canonical fault-process spec the resident runner trains
        under (fault/processes/) — what a request's optional `process`
        pin is compared against."""
        return self.runner._process_canonical()

    def pinned(self) -> Dict[str, str]:
        """The canonical pinned program set this service compiled —
        what the fleet worker table registers and the router matches
        request pins against."""
        mesh_axes = dict(getattr(self.runner.mesh, "shape", {}) or {})
        mesh_desc = ("single" if not mesh_axes
                     or all(v == 1 for v in mesh_axes.values())
                     else ",".join(f"{k}={v}"
                                   for k, v in sorted(mesh_axes.items())))
        return {
            "process": self._process_canonical(),
            "dtype_policy": str(self.runner.dtype_policy or "f32"),
            "net": self.net_name,
            "tiles": self.runner._tile_canonical(),
            "mesh": mesh_desc,
        }

    def _admit_pending(self) -> int:
        if self.pause_admission or (self.admission_gate is not None
                                    and not self.admission_gate()):
            # a hot swap is queued (serve/fleet/): pending requests
            # wait for the rebuilt service whose pins they match
            return 0
        admitted = 0
        for rid in self.spool.pending_ids():
            try:
                raw = self.spool.read(rid)
            except ValueError as e:
                # junk bytes dropped into pending/: quarantine the
                # file (fresh done/ payload; the original content is
                # unparseable) so one corrupt submission can never
                # crash — or spin — the shared resident server
                entry = self.spool.quarantine(
                    rid, f"unparseable request file: {e}")
                with self._stats_lock:
                    self._requests[rid] = dict(entry, cfg_ids=[],
                                               configs_total=0, done=0,
                                               tenant="default")
                self._emit_request(self._requests[rid], "rejected",
                                  reason=entry["reason"])
                continue
            if raw is None:
                # with the poison dir attached the read QUARANTINES
                # torn bytes instead of raising — the request still
                # owes a terminal record, so reject it loudly (same
                # contract as the ValueError arm below)
                moves = self.spool.drain_poisoned()
                mine = [m for m in moves if m["request"] == rid]
                self.spool.poisoned.extend(
                    m for m in moves if m["request"] != rid)
                if mine:
                    entry = self.spool.quarantine(
                        rid, "unparseable request file quarantined "
                             f"to {mine[0]['moved_to']}: "
                             f"{mine[0]['reason']}")
                    with self._stats_lock:
                        self._requests[rid] = dict(
                            entry, cfg_ids=[], configs_total=0,
                            done=0, tenant="default")
                    self._emit_request(self._requests[rid],
                                       "rejected",
                                       reason=entry["reason"])
                continue
            try:
                # raw files may be dropped into pending/ by anything
                # that can write the filesystem — re-validate here
                req = normalize_request(dict(raw, id=rid),
                                        self.default_iters)
            except ValueError as e:
                self._reject(dict(raw, id=rid,
                                  tenant=str(raw.get("tenant")
                                             or "default")),
                             f"invalid request: {e}")
                continue
            if "submit_seen" not in raw:
                # spooled directly (no front-door submit() call): the
                # lifecycle still starts with a submitted record
                self._emit_request(req, "submitted",
                                   configs=len(req["configs"]))
                self.spool.update(rid, "pending", {"submit_seen": True})
            if req.get("inject_nan") is not None \
                    and not self.allow_inject:
                self._reject(req, "inject_nan is a test hook "
                                  "(service started without "
                                  "allow_inject)")
                continue
            want_proc = req.get("process")
            if want_proc is not None:
                # the resident lane pool trains ONE compiled fault-
                # process stack; a request pinning a different physics
                # is refused rather than silently mis-served. The pin
                # is compared CANONICALIZED (FaultSpec normalizes stack
                # order and param formatting) so any equivalent
                # spelling of the same physics is accepted.
                from ..fault.processes import FaultSpec
                mine = self._process_canonical()
                try:
                    want_canon = FaultSpec.parse(want_proc).canonical()
                except Exception as e:
                    self._reject(req, f"unparseable fault-process pin "
                                      f"{want_proc!r}: {e}")
                    continue
                if want_canon != mine:
                    self._reject(req, f"request pins fault process "
                                      f"{want_canon!r} but this "
                                      f"service runs {mine!r}")
                    continue
            want_tiles = req.get("tiles")
            if want_tiles is not None:
                # same contract as the physics pin: the resident lane
                # pool compiled ONE tiled crossbar mapping
                # (fault/mapping.py) — its fault draws and per-tile
                # ADC reads are baked into the warm program — so a
                # request pinning a different mapping is refused at
                # admission. Compared CANONICALIZED so equivalent
                # spellings are accepted.
                from ..fault.mapping import TileSpec
                mine_t = self.runner._tile_canonical()
                try:
                    want_t = TileSpec.parse(want_tiles).canonical()
                except Exception as e:
                    self._reject(req, f"unparseable tile-mapping pin "
                                      f"{want_tiles!r}: {e}")
                    continue
                if want_t != mine_t:
                    self._reject(req, f"request pins tile mapping "
                                      f"{want_t!r} but this service "
                                      f"maps crossbars as {mine_t!r}")
                    continue
            want_dp = req.get("dtype_policy")
            if want_dp is not None:
                # same contract again: the lane pool compiled ONE
                # quantized sweep mode ("f32" = no policy)
                mine_dp = str(self.runner.dtype_policy or "f32")
                if want_dp != mine_dp:
                    self._reject(req, f"request pins dtype_policy "
                                      f"{want_dp!r} but this service "
                                      f"runs {mine_dp!r}")
                    continue
            want_net = req.get("net")
            if want_net is not None and want_net != self.net_name:
                self._reject(req, f"request pins net {want_net!r} but "
                                  f"this service trains "
                                  f"{self.net_name!r}")
                continue
            extra = req["iters"] * len(req["configs"])
            projected = self._projected_seconds(extra)
            at_risk = (self.slo_seconds > 0 and projected
                       and projected > self.slo_seconds)
            if at_risk and self.admission == "reject":
                self._reject(req, f"projected turnaround {projected:.0f}"
                                  f" s exceeds the {self.slo_seconds:g}"
                                  " s SLO window", projected)
                continue
            # scheduling quantum: budgets are rounded up to a chunk
            # multiple so every lane's remaining work stays a multiple
            # of the compiled chunk length (one executable, no
            # per-request recompiles)
            granted = -(-req["iters"] // self.chunk) * self.chunk
            ids = self.runner.submit_configs(req["configs"],
                                            budget=granted)
            entry = {
                "id": rid, "tenant": req["tenant"],
                "cfg_ids": ids, "iters": req["iters"],
                "iters_granted": granted,
                "configs_total": len(ids), "done": 0,
                "submit_time": float(req.get("submit_time",
                                             time.time())),
                "admit_time": time.time(), "start_time": None,
                "status": "admitted", "results": {},
                # the admission controller's projection, kept so the
                # terminal record (and the SLO ledger) can compare
                # projected vs achieved turnaround
                "projected_s": projected,
                "inject_nan": req.get("inject_nan"),
                "injected_attempt": {},
            }
            with self._stats_lock:
                self._requests[rid] = entry
                for cfg in ids:
                    self._cfg_req[cfg] = rid
            self.spool.claim(rid, {"cfg_ids": ids,
                                   "iters": req["iters"],
                                   "iters_granted": granted,
                                   "status": "admitted"})
            self._emit_request(entry, "admitted", configs=len(ids),
                              projected_s=projected,
                              reason=("slo at risk (queued anyway)"
                                      if at_risk else None))
            admitted += 1
        return admitted

    def _reject(self, req: dict, reason: str,
                projected: Optional[float] = None):
        rid = req["id"]
        self.spool.finish(rid, {"status": "rejected",
                                "reason": reason}, src="pending")
        entry = {"id": rid,
                 "tenant": str(req.get("tenant") or "default"),
                 "cfg_ids": [],
                 "configs_total": len(req.get("configs") or []),
                 "done": 0, "status": "rejected",
                 "submit_time": float(req.get("submit_time")
                                      or time.time())}
        with self._stats_lock:
            self._requests[rid] = entry
        self._emit_request(entry, "rejected", reason=reason,
                          projected_s=projected)

    def _projected_seconds(self, extra_iters: int = 0
                           ) -> Optional[float]:
        """Backlog projection: config-iterations outstanding (active
        lanes' remaining budgets + queued configs' full budgets +
        `extra_iters`) over the measured lane-pool rate. None until a
        dispatch rate has been measured (everything admits)."""
        if self._steps_per_sec <= 0:
            return None
        h = self.runner._healing
        backlog = int(extra_iters)
        for lane in range(self.runner.n):
            cfg = int(h.lane_cfg[lane])
            if cfg >= 0 and lane not in h.benign:
                backlog += max(self.runner._cfg_budget_of(cfg)
                               - int(h.lane_done[lane]), 0)
        for e in h.pending:
            backlog += self.runner._cfg_budget_of(int(e["config"]))
        rate = self._steps_per_sec * self.runner.n   # lane-iters/sec
        return backlog / rate if rate > 0 else None

    def _tenant_occupancy(self) -> Dict[str, int]:
        h = self.runner._healing
        occ: Dict[str, int] = {}
        for lane in range(self.runner.n):
            cfg = int(h.lane_cfg[lane])
            if cfg >= 0 and lane not in h.benign:
                t = self._tenant_of_cfg(cfg)
                occ[t] = occ.get(t, 0) + 1
        return occ

    def _account_beat(self, occupied: Dict[str, int], dt: float):
        """Per-tenant lane-share accounting at the chunk boundary, and
        the dispatch-rate EMA the admission controller divides by."""
        # exact lane-iteration occupancy per beat (observe/spans.py
        # OccupancyAggregator; the fleet bar is >90 % sustained)
        self._occ.add_counts(sum(occupied.values()), self.runner.n,
                             weight=self.chunk)
        for tenant, lanes in occupied.items():
            self._tenant_lane_iters[tenant] = (
                self._tenant_lane_iters.get(tenant, 0)
                + lanes * self.chunk)
        if dt > 0:
            if self._first_timed_beat:
                # this beat paid the chunk executable's jit compile
                # (seconds on a beat that steady-states in ms) —
                # seeding the EMA from it would project turnarounds
                # ~100x too slow and spuriously reject every request
                # under --admission reject until the EMA recovered
                self._first_timed_beat = False
                return
            rate = self.chunk / dt
            self._steps_per_sec = (rate if self._steps_per_sec <= 0
                                   else 0.7 * self._steps_per_sec
                                   + 0.3 * rate)

    def _tenant_of_cfg(self, cfg: int) -> str:
        rid = self._cfg_req.get(int(cfg))
        if rid is None:
            return "default"
        return self._requests[rid]["tenant"]

    def _weight(self, tenant: str) -> float:
        w = self.tenant_weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def _fair_order(self, entries, lane_map):
        """Weighted-fair refill: hand each freed lane to the eligible
        config whose tenant currently holds the smallest
        weight-normalized lane share; ties break by (config id,
        attempt) = submission order. Greedy water-filling — after each
        pick the tenant's share grows, so a queue of one tenant cannot
        starve the others no matter how many configs it spooled
        first. Only the freed lanes' worth of picks is consumed this
        boundary, so the greedy scan stops there — the backlog tail
        keeps its (config, attempt) submission order."""
        occ: Dict[str, float] = {}
        free = 0
        for cfg in lane_map:
            if cfg >= 0:
                t = self._tenant_of_cfg(cfg)
                occ[t] = occ.get(t, 0.0) + 1.0
            else:
                free += 1
        work = list(entries)
        out = []
        while work and len(out) < free:
            best = min(work, key=lambda e: (
                occ.get(self._tenant_of_cfg(e["config"]), 0.0)
                / self._weight(self._tenant_of_cfg(e["config"])),
                e["config"], e["attempt"]))
            work.remove(best)
            out.append(best)
            t = self._tenant_of_cfg(best["config"])
            occ[t] = occ.get(t, 0.0) + 1.0
        return out + work

    # ------------------------------------------------------------------
    # harvest + lifecycle records

    def _on_lane_complete(self, cfg: int, lane: int, result: dict):
        """Runner hook, fired BEFORE a harvested lane is freed: capture
        the completed config's fault-state rows while they are still
        this config's (the refill overwrites them)."""
        if not self.save_fault_results:
            return
        import numpy as np
        from ..fault import engine as fault_engine
        rows = {}
        for name, v in fault_engine.iter_state_leaves(
                self.runner.fault_states):
            # .copy() is load-bearing: on the CPU backend np.asarray
            # of the temporary `v[lane]` can be a ZERO-COPY view of an
            # XLA buffer that is freed as soon as the jax array is
            # collected — the npz written at harvest (beats later)
            # would then serialize reused memory
            rows[name] = np.asarray(v[lane]).copy()
        self._lane_results[int(cfg)] = rows

    def _save_fault_rows(self, rid: str, cfg: int):
        rows = self._lane_results.pop(int(cfg), None)
        if rows is None:
            return None
        import numpy as np
        name = f"{rid}.cfg{cfg}.faults.npz"
        path = os.path.join(self.dir, "requests", name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **rows)
        os.replace(tmp, path)
        return name

    def _harvest(self):
        """Fold the runner's completion ledger into the request table:
        per-config `config_done` records, `started` transitions, and
        terminal completed/failed records with the submit->terminal
        latency (the SLO-facing number)."""
        rep = self.runner.config_report()
        done = {**rep["completed"], **rep["failed"]}
        active = rep["active"]
        now = time.time()
        for rid in list(self._active_ids()):
            entry = self._requests[rid]
            if entry["status"] == "admitted" \
                    and any(c in active or c in done
                            for c in entry["cfg_ids"]):
                entry["status"] = "running"
                entry["start_time"] = now
                self._emit_request(
                    entry, "started",
                    queue_s=max(now - entry["submit_time"], 0.0))
            for cfg in entry["cfg_ids"]:
                key = str(cfg)
                if key in entry["results"] or cfg not in done:
                    continue
                v = dict(done[cfg])
                if self.save_fault_results:
                    fname = self._save_fault_rows(rid, cfg)
                    if fname:
                        v["fault_npz"] = fname
                entry["results"][key] = v
                entry["done"] = len(entry["results"])
                self._emit_request(entry, "config_done", config=cfg,
                                  status=v["status"],
                                  done=entry["done"],
                                  configs=entry["configs_total"])
            if entry["done"] == entry["configs_total"]:
                failed = [c for c, v in entry["results"].items()
                          if v["status"] == "failed"]
                entry["status"] = "failed" if failed else "completed"
                entry["latency_s"] = max(now - entry["submit_time"],
                                         0.0)
                reason = None
                if failed:
                    reason = "; ".join(
                        f"config {c}: "
                        f"{entry['results'][c].get('diagnosis', '?')}"
                        for c in failed)
                self.spool.finish(rid, {
                    "status": entry["status"],
                    "results": entry["results"],
                    "latency_s": entry["latency_s"],
                    "reason": reason})
                # SLO burn-rate ledger: achieved turnaround vs the
                # admission EMA's projection (stats()["slo"])
                self._slo.record(entry["tenant"], entry["latency_s"],
                                 projected_s=entry.get("projected_s"))
                self._emit_request(entry, entry["status"],
                                  configs=entry["configs_total"],
                                  done=entry["done"],
                                  latency_s=entry["latency_s"],
                                  projected_s=entry.get("projected_s"),
                                  reason=reason)

    def _emit_request(self, entry: dict, event: str,
                      front_door: bool = False, **kw):
        from ..observe import make_request_record
        kw = {k: v for k, v in kw.items() if v is not None}
        rec = make_request_record(self.runner.iter, entry["id"],
                                  entry.get("tenant", "default"),
                                  event, **kw)
        self._trace_request(entry, event, rec)
        _append_jsonl(os.path.join(self.dir, "requests",
                                   f"{entry['id']}.jsonl"), rec)
        if front_door:
            # called on the socket thread: the shared metrics sink is
            # unlocked and the loop thread may be mid-write — queue
            # the record for the next beat instead of interleaving
            with self._stats_lock:
                self._front_records.append(rec)
            return
        self._log_service_record(rec)

    def _log_service_record(self, rec: dict):
        if self.solver._metrics_enabled \
                and self.solver.metrics_logger is not None:
            self.solver.metrics_logger.log(rec)

    def _trace_request(self, entry: dict, event: str, rec: dict):
        """Request lifecycle on the span timeline: one ASYNC span per
        request (submitted/resumed -> terminal, linked by request id —
        it outlives any one beat and thread) plus an instant per
        transition. Thread-safe: `submitted` can land on the socket
        thread."""
        tr = self._tracer
        if tr is None:
            return
        rid = entry["id"]
        it = int(rec.get("iter", 0))
        tenant = entry.get("tenant", "default")
        if event in ("submitted", "resumed"):
            tr.async_begin("request", id=rid, cat="request",
                           iteration=it, args={"tenant": tenant})
        tr.instant(event, cat="request", iteration=it, id=rid,
                   args={"tenant": tenant})
        if event in _TERMINAL + ("preempted",):
            args = {"tenant": tenant, "event": event}
            if "latency_s" in rec:
                args["latency_s"] = rec["latency_s"]
            tr.async_end("request", id=rid, cat="request",
                         iteration=it, args=args)

    def _drain_spans(self):
        """Route not-yet-drained span records into the service-wide
        metrics stream (loop thread / close only — same single-writer
        discipline as `_flush_front_records`). The runner drains the
        shared tracer at every step() return too; the tracer's cursor
        makes the two drains disjoint."""
        if self._tracer is None:
            return
        for rec in self._tracer.drain_records():
            self._log_service_record(rec)

    def _flush_front_records(self):
        """Drain front-door-queued records into the service-wide
        metrics stream (loop thread / close only)."""
        with self._stats_lock:
            recs, self._front_records = self._front_records, []
        for rec in recs:
            self._log_service_record(rec)

    # ------------------------------------------------------------------
    # NaN-injection test hook (check_serve_contract.py)

    def _maybe_inject(self):
        """Poison the first config of any `inject_nan` request whose
        lane has reached the requested virtual iteration (once per
        attempt for "always", once total otherwise) — the deterministic
        failure the CI guard drives through the retry machinery."""
        if not self.allow_inject:
            return
        rep = None
        for entry in self._requests.values():
            spec = entry.get("inject_nan")
            if spec is None or entry["status"] in _TERMINAL \
                    or not entry["cfg_ids"]:
                continue
            if isinstance(spec, dict):
                at_iter = int(spec.get("iter", 0))
                always = bool(spec.get("always"))
            else:
                at_iter, always = int(spec), False
            cfg = entry["cfg_ids"][0]
            if rep is None:
                rep = self.runner.config_report()
            info = rep["active"].get(cfg)
            if info is None or info["done"] < at_iter:
                continue
            attempt = info["attempt"]
            seen = entry["injected_attempt"]
            if seen and (not always or seen.get("attempt") == attempt):
                continue
            self._poison_lane(info["lane"])
            entry["injected_attempt"] = {"attempt": attempt}
            print(f"Injected NaN into request {entry['id']} config "
                  f"{cfg} (lane {info['lane']}, attempt {attempt})",
                  flush=True)

    def _poison_lane(self, lane: int):
        import jax
        import jax.numpy as jnp
        import numpy as np
        key = self.solver._fault_keys[0]
        layer, slot = key.rsplit("/", 1)
        orig = self.runner.params[layer][int(slot)]
        w = np.array(orig)
        w[lane].flat[0] = np.nan
        self.runner.params[layer][int(slot)] = jax.device_put(
            jnp.asarray(w), orig.sharding)

    # ------------------------------------------------------------------
    # stats snapshot + state persistence + drain/resume

    def _update_stats_view(self):
        h = self.runner._healing
        occupied = sum(1 for lane in range(self.runner.n)
                       if h.lane_cfg[lane] >= 0
                       and lane not in h.benign)
        with self._stats_lock:
            self._stats_view = {
                "lanes": self.runner.n,
                "occupied_lanes": occupied,
                "pending_configs": len(h.pending),
                "steps_per_sec": round(self._steps_per_sec, 4),
                "projected_s": self._projected_seconds(),
                "slo_seconds": self.slo_seconds or None,
                "admission": self.admission,
                "tenant_lane_iters": dict(self._tenant_lane_iters),
                "requests": {
                    s: sum(1 for e in self._requests.values()
                           if e["status"] == s)
                    for s in ("admitted", "running", "completed",
                              "failed", "rejected", "preempted")},
                "iter": int(self.runner.iter),
                # utilization layer (observe/spans.py): exact
                # lane-iteration occupancy across every beat so far,
                # and the per-tenant SLO ledger (achieved turnaround,
                # violation/burn rates, projection bias vs the
                # admission EMA) — None until a beat / a terminal
                # request lands
                "occupancy": self._occ.summary(),
                "slo": self._slo.summary(),
                # crossbar health plane (observe/health.py): the
                # runner's wear-ledger rollup — None until the first
                # census (or with health_every=0), so scrapers can
                # tell "no data" from "healthy"
                "health": self.runner.health_summary(),
            }

    def _state_path(self) -> str:
        return os.path.join(self.dir, "state.json")

    def _ckpt_path(self) -> str:
        return os.path.join(self.dir, "checkpoint.npz")

    def _write_state(self, with_checkpoint: bool = False):
        state = {
            "schema_version": 1,
            "requests": self._requests,
            "tenant_lane_iters": self._tenant_lane_iters,
            "has_checkpoint": bool(with_checkpoint),
            "iter": int(self.runner.iter),
        }
        _atomic_write(self._state_path(), state)

    def _drain_exit(self) -> int:
        """Stop admitting, checkpoint in-flight lanes + request table,
        emit `preempted` records, report the exit code. The DRAIN
        control file (the durable drain op) is consumed."""
        try:
            os.remove(os.path.join(self.dir, "DRAIN"))
        except OSError:
            pass
        self.drained = True
        in_flight = self._active_ids()
        if not in_flight and self.runner.healing_complete():
            try:
                os.remove(self._ckpt_path())
            except OSError:
                pass
            self._write_state()
            print("Sweep service drained idle (no in-flight "
                  "requests); exit 0", flush=True)
            return 0
        self.runner.checkpoint(self._ckpt_path())
        for rid in in_flight:
            # visible in stats()/state.json; _resume recomputes
            # admitted/running from start_time when the lanes restore.
            # The SPOOL file gets the status too: a client polling a
            # drained (exited) service has only the spool to read, and
            # `wait`'s distinct preempted-vs-pending exit codes depend
            # on seeing it there.
            self._requests[rid]["status"] = "preempted"
            try:
                self.spool.update(rid, "active",
                                  {"status": "preempted"})
            except OSError:
                pass
        self._write_state(with_checkpoint=True)
        for rid in in_flight:
            entry = self._requests[rid]
            self._emit_request(entry, "preempted",
                              configs=entry["configs_total"],
                              done=entry.get("done", 0))
        print(f"Sweep service drained with {len(in_flight)} in-flight "
              f"request(s) checkpointed; exit {DRAIN_EXIT} — restart "
              "with the same --service-dir to resume", flush=True)
        return DRAIN_EXIT

    def _resume(self):
        """Restart path: restore the lane pool from the drain
        checkpoint + request table. Requests whose configs the
        checkpoint does not know (admitted after the last checkpoint —
        only possible after a crash, not a graceful drain) are
        re-admitted fresh: at-least-once completion, with the
        re-execution being a legitimate fresh Monte-Carlo attempt."""
        try:
            with open(self._state_path()) as f:
                state = json.load(f)
        except ValueError as e:
            # torn state.json (a crash mid-write on a filesystem
            # without atomic rename): quarantine it and resume from
            # the spool alone — active requests re-admit fresh below
            dst = os.path.join(self.dir, "poison", "state.json")
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = os.path.join(self.dir, "poison",
                                   f"state.json.{n}")
            try:
                os.replace(self._state_path(), dst)
            except OSError:
                pass
            print(f"Sweep service: torn state.json quarantined to "
                  f"{dst} ({e}); resuming from the spool", flush=True)
            state = {}
        self._tenant_lane_iters = {
            str(k): int(v)
            for k, v in state.get("tenant_lane_iters", {}).items()}
        table = state.get("requests", {})
        restored = False
        if state.get("has_checkpoint") \
                and os.path.exists(self._ckpt_path()):
            self.runner.restore(self._ckpt_path())
            restored = True
        known = set()
        if restored:
            rep = self.runner.config_report()
            known = (set(rep["completed"]) | set(rep["failed"])
                     | set(rep["active"])
                     | {int(e["config"]) for e in rep["pending"]})
        for rid, entry in table.items():
            entry = dict(entry)
            entry.setdefault("injected_attempt", {})
            if entry["status"] in _TERMINAL:
                self._requests[rid] = entry
                continue
            if restored and all(int(c) in known
                                for c in entry["cfg_ids"]):
                entry["status"] = ("admitted"
                                   if entry.get("start_time") is None
                                   else "running")
                self._requests[rid] = entry
                for cfg in entry["cfg_ids"]:
                    self._cfg_req[int(cfg)] = rid
                try:
                    # clear the drain's persisted "preempted" so spool
                    # readers see the request live again
                    self.spool.update(rid, "active",
                                      {"status": entry["status"]})
                except OSError:
                    pass
                self._emit_request(entry, "resumed",
                                  configs=entry["configs_total"],
                                  done=entry.get("done", 0))
                continue
            # unknown to the restored lanes: re-admit the whole
            # request fresh from its active spool file
            req = self.spool.read(rid)
            if req is None:
                continue
            if req.get("state") == "done":
                # crash landed between spool.finish and the beat's
                # state write: the spool (source of truth) already has
                # the terminal payload — adopt it, don't re-run
                entry.update(status=req.get("status", "completed"),
                             results=req.get("results",
                                             entry.get("results", {})))
                entry["done"] = len(entry.get("results") or {})
                self._requests[rid] = entry
                continue
            self._readmit(rid, req, entry,
                          "re-admitted (no checkpoint covered these "
                          "configs)")
        # reconcile spool active/ against the table: a request CLAIMED
        # in a beat that crashed before its state write has an active/
        # file and no table entry — without this scan it would never
        # get lanes and never terminate (the at-least-once contract)
        for req in self.spool.active():
            rid = req.get("id")
            if not rid or rid in self._requests:
                continue
            entry = {
                "id": rid,
                "tenant": str(req.get("tenant") or "default"),
                "iters": req.get("iters", self.default_iters),
                "iters_granted": req.get("iters_granted"),
                "configs_total": len(req.get("configs") or []),
                "submit_time": float(req.get("submit_time")
                                     or time.time()),
                "admit_time": time.time(),
                "inject_nan": req.get("inject_nan"),
                "injected_attempt": {},
            }
            self._readmit(rid, req, entry,
                          "re-admitted (claimed before the crashed "
                          "service recorded it)")
        n = len([r for r in self._requests.values()
                 if r["status"] not in _TERMINAL])
        print(f"Sweep service resumed at iteration "
              f"{self.runner.iter}: {n} in-flight request(s)",
              flush=True)

    def _readmit(self, rid: str, req: dict, entry: dict,
                 reason: str):
        """Allocate fresh lanes for a request whose previous configs
        no checkpoint covers (at-least-once completion: the re-run is
        a legitimate fresh Monte-Carlo attempt)."""
        granted = int(entry.get("iters_granted")
                      or -(-int(req.get("iters", self.default_iters))
                           // self.chunk) * self.chunk)
        ids = self.runner.submit_configs(req["configs"],
                                         budget=granted)
        entry.update(cfg_ids=ids, iters_granted=granted,
                     status="admitted", done=0, results={},
                     start_time=None)
        with self._stats_lock:
            self._requests[rid] = entry
            for cfg in ids:
                self._cfg_req[cfg] = rid
        self.spool.update(rid, "active", {"cfg_ids": ids,
                                          "iters_granted": granted,
                                          "status": "admitted"})
        self._emit_request(entry, "resumed",
                          configs=entry["configs_total"], done=0,
                          reason=reason)

    def suspend_socket(self):
        """Stop the Unix-socket front door without closing the service
        (serve/fleet/ parks dormant resident-program services; two
        services must never race for one socket path)."""
        if self._sock_server is not None:
            # the successor service owns the socket path from here —
            # a handler outliving stop()'s bounded join must not
            # unlink the re-bound socket on its way out
            self._sock_server._unlink_on_exit = False
            self._sock_server.stop()
            self._sock_server = None

    def resume_socket(self, socket_path: Optional[str] = None):
        """Re-open the front door after `suspend_socket` (fleet
        reactivation)."""
        if self._sock_server is not None or self._closed:
            return
        path = socket_path or os.path.join(self.dir, "service.sock")
        if len(path) <= _MAX_SOCK_PATH:
            self._sock_server = _SocketServer(self, path)
            self._sock_server.start()

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._sock_server is not None:
            self._sock_server.stop()
        # the socket thread is down: any still-queued front-door
        # records can flush without an interleaving writer
        self._flush_front_records()
        logger = self.solver.metrics_logger
        self.runner.close()
        if logger is not None:
            logger.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class _SocketServer(threading.Thread):
    """Local Unix-socket front door: one JSON object per line in, one
    per line out. Ops: ping, submit {request}, status {id},
    result {id}, stats, drain. Runs on its own thread and touches only
    the spool + lock-protected snapshots — never the runner."""

    def __init__(self, service: SweepService, path: str):
        super().__init__(daemon=True, name="serve-frontdoor")
        self.service = service
        self.path = path
        #: cleared by suspend_socket: a handler can outlive stop()'s
        #: bounded join (conn recv timeout 5 s > join 2 s), and this
        #: thread's exit path must then NOT unlink a path a successor
        #: server (fleet hot swap) has already re-bound
        self._unlink_on_exit = True
        try:
            os.remove(path)
        except OSError:
            pass
        self._sock = socket_mod.socket(socket_mod.AF_UNIX,
                                       socket_mod.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(8)
        self._sock.settimeout(0.25)
        self._stopping = threading.Event()

    def run(self):
        while not self._stopping.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket_mod.timeout:
                continue
            except OSError:
                break
            try:
                self._handle(conn)
            except Exception:
                pass
            finally:
                conn.close()
        self._sock.close()
        if self._unlink_on_exit:
            try:
                os.remove(self.path)
            except OSError:
                pass

    def _handle(self, conn):
        conn.settimeout(5.0)
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                return
            buf += chunk
            if len(buf) > 4 << 20:
                raise ValueError("request line too large")
        line = buf.split(b"\n", 1)[0]
        try:
            msg = json.loads(line.decode())
            resp = self._dispatch(msg)
        except Exception as e:
            resp = {"ok": False, "error": str(e)}
        conn.sendall((json.dumps(resp) + "\n").encode())

    def _dispatch(self, msg: dict) -> dict:
        op = msg.get("op")
        svc = self.service
        if op == "ping":
            return {"ok": True, "pong": True, "dir": svc.dir}
        if op == "submit":
            out = svc.submit(msg.get("request") or {})
            return {"ok": True, **out}
        if op in ("status", "result"):
            rid = msg.get("id", "")
            req = svc.status(rid)
            if req is None:
                return {"ok": False,
                        "error": f"unknown request id {rid!r}"}
            return {"ok": True, "request": req}
        if op == "stats":
            return {"ok": True, "stats": svc.stats()}
        if op == "metrics":
            # Prometheus exposition built ON DEMAND from the lock-
            # protected stats snapshot: the serve loop does no extra
            # work when nobody scrapes, so a monitored run stays
            # byte-identical to an unmonitored one.
            from ..observe.metrics_registry import registry_from_stats
            return {"ok": True,
                    "exposition": registry_from_stats(svc.stats()).render()}
        if op == "drain":
            svc.drain()
            return {"ok": True, "draining": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def stop(self):
        self._stopping.set()
        self.join(timeout=2.0)


def main(argv=None) -> int:
    """`python -m rram_caffe_simulation_tpu.serve` / `caffe serve` —
    run a sweep service until drained (SIGTERM, the client `drain` op,
    or `--drain-when-idle`)."""
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(
        prog="rram-sweep-serve",
        description="resident fault-sweep service (see serve/service.py)")
    p.add_argument("--solver", required=True,
                   help="solver prototxt with a pinned random_seed, a "
                        "gaussian failure_pattern, and a "
                        "materializable Data layer")
    p.add_argument("--service-dir", required=True,
                   help="durable service root: spool/, requests/, "
                        "metrics.jsonl, checkpoint + state on drain")
    p.add_argument("--lanes", type=int, default=8,
                   help="vectorized config lanes held warm (the "
                        "continuous-batching pool width)")
    p.add_argument("--chunk", type=int, default=8,
                   help="scanned iterations per dispatch = the "
                        "scheduling quantum (budgets round up to it)")
    p.add_argument("--default-iters", type=int, default=100,
                   help="iteration budget for requests that do not "
                        "carry their own 'iters'")
    p.add_argument("--max-retries", type=int, default=1)
    p.add_argument("--retry-backoff", type=int, default=0)
    p.add_argument("--slo-seconds", type=float, default=0.0,
                   help="SLO window for the admission controller; 0 "
                        "disables the projection check")
    p.add_argument("--admission", default="queue",
                   choices=["queue", "reject"],
                   help="what to do when the projected backlog "
                        "turnaround exceeds --slo-seconds")
    p.add_argument("--tenant-weight", action="append", default=[],
                   metavar="TENANT=W",
                   help="weighted-fair share for a tenant (repeatable;"
                        " default weight 1)")
    p.add_argument("--poll-interval", type=float, default=0.5)
    p.add_argument("--pipeline-depth", type=int, default=0)
    p.add_argument("--no-socket", action="store_true",
                   help="disable the Unix-socket front door (spool "
                        "submissions only)")
    p.add_argument("--drain-when-idle", action="store_true",
                   help="exit 0 once the spool is empty and every "
                        "request is terminal (batch/CI mode) instead "
                        "of waiting for more work")
    p.add_argument("--max-beats", type=int, default=0,
                   help="stop after N scheduling beats (test hook); "
                        "0 = unlimited")
    p.add_argument("--allow-inject", action="store_true",
                   help="TEST HOOK (check_serve_contract.py): honor "
                        "requests' inject_nan poisoning field")
    p.add_argument("--save-fault-results", action="store_true",
                   help="write each completed config's fault-state "
                        "rows to requests/<id>.cfg<N>.faults.npz "
                        "(the byte-identity evidence the CI guard "
                        "compares)")
    p.add_argument("--fault-process", default=None,
                   help="fault-process spec the lane pool compiles "
                        "(fault/processes/ registry; default "
                        "endurance_stuck_at) — the service's pinned "
                        "physics, matched against request 'process' "
                        "pins")
    p.add_argument("--tiles", default=None,
                   help="tiled crossbar mapping spec (fault/mapping.py;"
                        " default 1x1) — the pinned mapping")
    p.add_argument("--dtype-policy", default=None,
                   help="quantized sweep mode ('ternary' | 'int8'; "
                        "default f32) — the pinned precision")
    p.add_argument("--net-name", default=None,
                   help="short net name for the worker table / request "
                        "'net' pins (default: solver file basename)")
    p.add_argument("--mesh", default="",
                   help="config mesh for the lane pool, e.g. "
                        "'config=4' or 'config=all' — the warm lanes "
                        "shard over that many local chips as one "
                        "GSPMD program; empty = single device")
    p.add_argument("--trace", action="store_true",
                   help="arm the span tracer (observe/spans.py): "
                        "request lifetimes + beat/dispatch/consume "
                        "spans as schema-validated `span` records in "
                        "metrics.jsonl, and a Perfetto-loadable "
                        "Chrome-trace file on drain")
    p.add_argument("--profile-dir", default="",
                   help="where the Perfetto trace export lands "
                        "(default <service-dir>/trace); share it with "
                        "a jax.profiler capture to view host spans "
                        "alongside device traces")
    p.add_argument("--health-every", type=int, default=0,
                   help="crossbar wear-census cadence in iterations "
                        "(observe/health.py): emit schema-validated "
                        "`health` records + rram_health_* gauges; "
                        "0 = off")
    args = p.parse_args(argv)

    weights = {}
    for spec in args.tenant_weight:
        if "=" not in spec:
            p.error(f"--tenant-weight {spec!r} must be TENANT=WEIGHT")
        name, w = spec.rsplit("=", 1)
        weights[name] = float(w)

    service = SweepService(
        args.solver, args.service_dir, lanes=args.lanes,
        chunk=args.chunk, default_iters=args.default_iters,
        max_retries=args.max_retries, retry_backoff=args.retry_backoff,
        tenant_weights=weights, slo_seconds=args.slo_seconds,
        admission=args.admission, poll_interval_s=args.poll_interval,
        pipeline_depth=args.pipeline_depth,
        socket_path=None if args.no_socket else "",
        allow_inject=args.allow_inject,
        save_fault_results=args.save_fault_results,
        mesh=args.mesh or None,
        trace=args.trace, profile_dir=args.profile_dir or None,
        fault_process=args.fault_process, tile_spec=args.tiles,
        dtype_policy=args.dtype_policy, net_name=args.net_name,
        health_every=args.health_every)

    def _on_signal(signum, frame):
        service.drain()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(f"Sweep service up: {service.runner.n} lanes, chunk "
          f"{service.chunk}, spool {service.spool.root}", flush=True)
    try:
        code = service.serve(max_beats=args.max_beats or None,
                             drain_when_idle=args.drain_when_idle)
    finally:
        service.close()
    sys.stdout.flush()
    return code


if __name__ == "__main__":
    import sys
    sys.exit(main())

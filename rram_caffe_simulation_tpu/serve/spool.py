"""Durable filesystem spool — the sweep service's request queue.

A request is one JSON file; its lifecycle is a rename walk through the
state directories under ``<root>/spool``::

    pending/   submitted, not yet picked up by the service
    active/    admitted into the live lane work queue
    done/      terminal (completed / failed / rejected) — the file now
               carries the result payload too

Every write is temp-file + atomic-rename (a crash can never leave a
half-written request under a live name) and fsynced (the spool must
survive the SIGKILL that follows a preemption SIGTERM — same contract
as the sweep journal). Pending requests are processed in sorted
filename order; auto-generated ids are zero-padded nanosecond
timestamps, so "sorted" means "submission order" unless the caller
chooses their own ordering by naming ids explicitly (the CI guard
does, for determinism).

Because every write is atomic, an UNPARSEABLE file in a state
directory is never a half-finished write — it is corrupt bytes from
outside the contract (a torn direct write, disk damage, a chaos
injection). The owning consumer opens the spool with a `poison_dir`
and such files are quarantined there instead of crashing the beat
loop; read-only clients without one simply tolerate them (see
`Spool._poison`). A request present in TWO state directories is a
rename that died between its atomic destination write and its source
remove — `resolve_dual` finishes the move deterministically, which is
what makes the fleet controller's beat an idempotent journaled
transaction (ISSUE 20).

The spool is intentionally dependency-free (no jax) so clients — the
`serve_client` library, shell scripts, another host sharing a
filesystem — can submit without importing the framework.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional

#: request lifecycle states == spool subdirectory names
STATES = ("pending", "active", "done")

_ID_OK = set("abcdefghijklmnopqrstuvwxyz"
             "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.")


def make_request_id() -> str:
    """A sortable request id: zero-padded wall-clock nanoseconds (so
    lexicographic order == submission order) plus entropy against
    same-nanosecond collisions."""
    return f"r-{time.time_ns():020d}-{os.urandom(3).hex()}"


def normalize_request(req: dict, default_iters: int = 0) -> dict:
    """Validate + fill a request dict in place of a schema: `configs`
    must be a non-empty list of {mean?, std?} spec objects, `iters` a
    positive int (falls back to `default_iters`), `tenant` a short
    name, `id` spool-filename-safe. Returns a normalized copy; raises
    ValueError on junk — the front door refuses it before it ever
    reaches the spool."""
    if not isinstance(req, dict):
        raise ValueError("request must be a JSON object")
    out = dict(req)
    rid = out.setdefault("id", make_request_id())
    if not isinstance(rid, str) or not rid or len(rid) > 120 \
            or not set(rid) <= _ID_OK:
        raise ValueError(
            f"request id {rid!r} must be a non-empty string of "
            "[A-Za-z0-9._-], at most 120 chars (it becomes a spool "
            "filename)")
    tenant = out.setdefault("tenant", "default")
    if not isinstance(tenant, str) or not tenant or len(tenant) > 64:
        raise ValueError(f"tenant {tenant!r} must be a non-empty "
                         "string of at most 64 chars")
    configs = out.get("configs")
    if not isinstance(configs, list) or not configs:
        raise ValueError("request needs a non-empty 'configs' list of "
                         "{mean, std} spec objects")
    specs = []
    for i, spec in enumerate(configs):
        if not isinstance(spec, dict):
            raise ValueError(f"configs[{i}] is not an object")
        clean = {}
        for key in ("mean", "std"):
            if key in spec:
                try:
                    clean[key] = float(spec[key])
                except (TypeError, ValueError):
                    raise ValueError(
                        f"configs[{i}].{key} is not a number: "
                        f"{spec[key]!r}") from None
        specs.append(clean)
    out["configs"] = specs
    proc = out.get("process")
    if proc is not None:
        # optional fault-process pin (fault/processes/ spec syntax):
        # the resident service trains ONE compiled process stack, so a
        # request naming a different one is refused at admission (the
        # service compares this string against its runner's canonical
        # spec) instead of silently training the wrong physics
        if not isinstance(proc, str) or not proc.strip() \
                or len(proc) > 256:
            raise ValueError(
                f"request process {proc!r} must be a non-empty "
                "fault-process spec string (at most 256 chars)")
        out["process"] = proc.strip()
    tiles = out.get("tiles")
    if tiles is not None:
        # optional tiled-crossbar-mapping pin (fault/mapping.py spec
        # syntax, e.g. "cells=256x256"): like the process pin, the
        # resident service trains ONE compiled tile mapping, so a
        # request naming a different one is refused at admission
        # (canonicalized comparison happens in the service — this
        # spool layer stays dependency-free)
        if not isinstance(tiles, str) or not tiles.strip() \
                or len(tiles) > 64:
            raise ValueError(
                f"request tiles {tiles!r} must be a non-empty tile-"
                "mapping spec string (at most 64 chars, e.g. '1x1' "
                "or 'cells=256x256')")
        out["tiles"] = tiles.strip()
    dp = out.get("dtype_policy")
    if dp is not None:
        # optional quantized-sweep-mode pin ("f32" | "ternary" |
        # "int8"): like the process pin, a resident lane pool compiles
        # ONE dtype policy, so a request naming a different one is
        # routed to a matching fleet worker (or hot-swaps one) rather
        # than silently served at the wrong precision. The legal-value
        # check happens at admission (the spool stays dependency-free).
        if not isinstance(dp, str) or not dp.strip() or len(dp) > 32:
            raise ValueError(
                f"request dtype_policy {dp!r} must be a non-empty "
                "string of at most 32 chars (e.g. 'f32', 'ternary')")
        out["dtype_policy"] = dp.strip()
    net = out.get("net")
    if net is not None:
        # optional net pin: the short name a fleet worker registered
        # its solver's net under — a request naming a different net is
        # routed/swapped, never silently trained on the wrong model
        if not isinstance(net, str) or not net.strip() \
                or len(net) > 128:
            raise ValueError(
                f"request net {net!r} must be a non-empty string of "
                "at most 128 chars (the worker-table net name)")
        out["net"] = net.strip()
    iters = out.get("iters") or default_iters
    if not iters:
        # no explicit budget and no default known HERE (e.g. the
        # client's durable spool fallback, which cannot see the
        # service's --default-iters): defer — the service re-validates
        # with its own default at pickup
        out.pop("iters", None)
    else:
        if not isinstance(iters, int) or isinstance(iters, bool) \
                or iters <= 0:
            raise ValueError(
                f"request iters must be a positive int, got "
                f"{out.get('iters')!r} (and the service has default "
                f"{default_iters})")
        out["iters"] = iters
    out.setdefault("submit_time", time.time())
    return out


def _atomic_write(path: str, payload: dict):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Spool:
    """The service-side view of the request queue (see module
    docstring). All mutation is rename-based and single-consumer: only
    the service moves files out of pending/.

    **Poison quarantine** (opt-in via `poison_dir`): every write is
    atomic, so an unparseable file in a state directory is never a
    half-finished write — it is genuinely corrupt bytes (a torn direct
    write from a crashed foreign producer, disk damage, or a chaos
    injection). With `poison_dir` set the OWNING consumer (service /
    fleet controller) moves such a file aside and keeps beating; the
    moves land in `self.poisoned` for the owner to alert on. Without
    it (read-only clients) a torn file is tolerated — `read` returns
    None, `active` skips it — but never relocated: only the single
    consumer may move files."""

    def __init__(self, root: str, poison_dir: Optional[str] = None):
        self.root = root
        self.poison_dir = poison_dir
        #: poison moves since the last `drain_poisoned()` call:
        #: {"request", "state", "moved_to", "reason"} dicts
        self.poisoned: List[dict] = []
        self.poison_total = 0
        for state in STATES:
            os.makedirs(os.path.join(root, state), exist_ok=True)
        if poison_dir:
            os.makedirs(poison_dir, exist_ok=True)

    def _dir(self, state: str) -> str:
        return os.path.join(self.root, state)

    def _path(self, state: str, request_id: str) -> str:
        return os.path.join(self._dir(state), f"{request_id}.json")

    def _poison(self, path: str, state: str, err: Exception):
        """Move an unparseable file out of the state directory (when
        this handle owns a poison dir) so the consumer loop never
        crashes — or spins — on the same corrupt bytes twice."""
        if not self.poison_dir:
            return
        name = os.path.basename(path)
        dst = os.path.join(self.poison_dir, f"{state}-{name}")
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(self.poison_dir, f"{state}-{name}.{n}")
        try:
            os.replace(path, dst)
        except OSError:
            return
        self.poison_total += 1
        self.poisoned.append({
            "request": name[:-len(".json")] if name.endswith(".json")
            else name,
            "state": state, "moved_to": dst, "reason": str(err)})

    def drain_poisoned(self) -> List[dict]:
        """Poison moves since the last drain (and clear the list) —
        the owner turns these into alert records."""
        out, self.poisoned = self.poisoned, []
        return out

    def submit(self, request: dict, default_iters: int = 0) -> str:
        """Validate + atomically spool a request into pending/.
        Returns the request id. Duplicate ids are refused (a resubmit
        must pick a new id — the old one's lifecycle is already on
        disk)."""
        req = normalize_request(request, default_iters)
        rid = req["id"]
        if self.state_of(rid) is not None:
            raise ValueError(f"request id {rid!r} already exists in "
                             "the spool")
        _atomic_write(self._path("pending", rid), req)
        return rid

    def pending_ids(self) -> List[str]:
        """Pending request ids in processing (filename) order."""
        names = sorted(n for n in os.listdir(self._dir("pending"))
                       if n.endswith(".json"))
        return [n[:-len(".json")] for n in names]

    def state_of(self, request_id: str) -> Optional[str]:
        for state in STATES:
            if os.path.exists(self._path(state, request_id)):
                return state
        return None

    def read(self, request_id: str) -> Optional[dict]:
        """The request's current payload, from whichever state dir it
        lives in (None when unknown). Corrupt bytes never raise: a
        torn file reads as None (and is quarantined when this handle
        owns a poison dir)."""
        for state in STATES:
            path = self._path(state, request_id)
            try:
                with open(path) as f:
                    return dict(json.load(f), state=state)
            except FileNotFoundError:
                continue
            except ValueError as e:
                self._poison(path, state, e)
                return None
        return None

    def claim(self, request_id: str, updates: Optional[dict] = None
              ) -> dict:
        """pending -> active (admission). Returns the payload, with
        `updates` merged + persisted (e.g. the allocated config
        ids)."""
        return self._advance(request_id, "pending", "active", updates)

    def finish(self, request_id: str, updates: Optional[dict] = None,
               src: str = "active") -> dict:
        """active (or pending, for rejections) -> done, merging the
        terminal result payload into the file."""
        return self._advance(request_id, src, "done", updates)

    def _advance(self, request_id: str, src: str, dst: str,
                 updates: Optional[dict]) -> dict:
        path = self._path(src, request_id)
        dst_path = self._path(dst, request_id)
        try:
            with open(path) as f:
                req = json.load(f)
        except FileNotFoundError:
            if os.path.exists(dst_path):
                # idempotent re-advance: a previous call (or a
                # controller that died between this advance and its
                # state write) already committed the move — the
                # destination file IS the record of that, so return
                # it instead of raising
                with open(dst_path) as f:
                    return json.load(f)
            raise
        if updates:
            req.update(updates)
        _atomic_write(dst_path, req)
        os.remove(path)
        return req

    def requeue(self, request_id: str,
                drop: tuple = ("cfg_ids", "iters_granted", "status",
                               "worker", "attempt",
                               "submit_seen")) -> dict:
        """active -> pending: put a claimed request back on the queue
        (the fleet controller's dead-worker path — at-least-once
        completion, lifted one level). The previous claimant's
        bookkeeping fields are dropped so the next pickup starts a
        fresh attempt; `submit_time` survives, so the request's
        terminal `latency_s` spans the WHOLE fleet turnaround
        including the failed attempt."""
        path = self._path("active", request_id)
        with open(path) as f:
            req = json.load(f)
        for key in drop:
            req.pop(key, None)
        req["requeues"] = int(req.get("requeues", 0)) + 1
        _atomic_write(self._path("pending", request_id), req)
        os.remove(path)
        return req

    def update(self, request_id: str, state: str, updates: dict
               ) -> dict:
        """Merge fields into a request file in place (no state move)."""
        path = self._path(state, request_id)
        with open(path) as f:
            req = json.load(f)
        req.update(updates)
        _atomic_write(path, req)
        return req

    def quarantine(self, request_id: str, reason: str) -> dict:
        """pending -> done for a file whose CONTENT cannot be parsed:
        the done/ payload is written fresh (the original bytes are
        junk) so the resident service never crashes — or spins — on a
        corrupt submission."""
        payload = {"id": request_id, "status": "rejected",
                   "reason": reason, "submit_time": time.time()}
        _atomic_write(self._path("done", request_id), payload)
        try:
            os.remove(self._path("pending", request_id))
        except FileNotFoundError:
            pass
        return payload

    def active(self) -> List[dict]:
        """Every active request payload, in filename order. Torn files
        are skipped (and quarantined when this handle owns a poison
        dir) — crash recovery must not crash on the crash's debris."""
        out = []
        for name in sorted(os.listdir(self._dir("active"))):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self._dir("active"), name)
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except FileNotFoundError:
                continue
            except ValueError as e:
                self._poison(path, "active", e)
        return out

    def dual_ids(self) -> List[str]:
        """Request ids present in MORE than one state directory — the
        signature of a rename walk (claim / requeue / finish) that
        died between its atomic destination write and its source
        remove. `resolve_dual` finishes the interrupted move."""
        seen: dict = {}
        for state in STATES:
            for name in os.listdir(self._dir(state)):
                if name.endswith(".json") \
                        and not name.count(".tmp."):
                    seen.setdefault(name[:-len(".json")],
                                    []).append(state)
        return sorted(r for r, states in seen.items()
                      if len(states) > 1)

    def resolve_dual(self, request_id: str) -> Optional[str]:
        """Finish a state move that crashed halfway (the request file
        exists under two state dirs). The atomic destination write is
        the commit point, so the DESTINATION always wins:

        - active + done: a `finish` died before removing active/ —
          done/ is terminal, drop the active copy;
        - pending + active: either a `claim` (pending -> active) or a
          `requeue` (active -> pending) died. The direction is
          recoverable from the requeue counter — a requeue writes its
          new pending copy with `requeues` bumped PAST the active
          copy's, a claim's active copy carries the same count as the
          pending file it came from. Torn halves lose to parseable
          ones.

        Returns the surviving state name (None when the request is
        not dual)."""
        def load(state):
            try:
                with open(self._path(state, request_id)) as f:
                    return json.load(f)
            except (FileNotFoundError, ValueError):
                return None

        def drop(state):
            try:
                os.remove(self._path(state, request_id))
            except FileNotFoundError:
                pass

        here = [s for s in STATES
                if os.path.exists(self._path(s, request_id))]
        if len(here) < 2:
            return here[0] if here else None
        if "done" in here:
            for state in here:
                if state != "done":
                    drop(state)
            return "done"
        pend, act = load("pending"), load("active")
        if act is None:
            drop("active")
            return "pending"
        if pend is None:
            drop("pending")
            return "active"
        if int(pend.get("requeues", 0)) > int(act.get("requeues", 0)):
            drop("active")      # crashed requeue: pending/ committed
            return "pending"
        drop("pending")         # crashed claim: active/ committed
        return "active"

"""Durable filesystem spool — the sweep service's request queue.

A request is one JSON file; its lifecycle is a rename walk through the
state directories under ``<root>/spool``::

    pending/   submitted, not yet picked up by the service
    active/    admitted into the live lane work queue
    done/      terminal (completed / failed / rejected) — the file now
               carries the result payload too

Every write is temp-file + atomic-rename (a crash can never leave a
half-written request under a live name) and fsynced (the spool must
survive the SIGKILL that follows a preemption SIGTERM — same contract
as the sweep journal). Pending requests are processed in sorted
filename order; auto-generated ids are zero-padded nanosecond
timestamps, so "sorted" means "submission order" unless the caller
chooses their own ordering by naming ids explicitly (the CI guard
does, for determinism).

The spool is intentionally dependency-free (no jax) so clients — the
`serve_client` library, shell scripts, another host sharing a
filesystem — can submit without importing the framework.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional

#: request lifecycle states == spool subdirectory names
STATES = ("pending", "active", "done")

_ID_OK = set("abcdefghijklmnopqrstuvwxyz"
             "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.")


def make_request_id() -> str:
    """A sortable request id: zero-padded wall-clock nanoseconds (so
    lexicographic order == submission order) plus entropy against
    same-nanosecond collisions."""
    return f"r-{time.time_ns():020d}-{os.urandom(3).hex()}"


def normalize_request(req: dict, default_iters: int = 0) -> dict:
    """Validate + fill a request dict in place of a schema: `configs`
    must be a non-empty list of {mean?, std?} spec objects, `iters` a
    positive int (falls back to `default_iters`), `tenant` a short
    name, `id` spool-filename-safe. Returns a normalized copy; raises
    ValueError on junk — the front door refuses it before it ever
    reaches the spool."""
    if not isinstance(req, dict):
        raise ValueError("request must be a JSON object")
    out = dict(req)
    rid = out.setdefault("id", make_request_id())
    if not isinstance(rid, str) or not rid or len(rid) > 120 \
            or not set(rid) <= _ID_OK:
        raise ValueError(
            f"request id {rid!r} must be a non-empty string of "
            "[A-Za-z0-9._-], at most 120 chars (it becomes a spool "
            "filename)")
    tenant = out.setdefault("tenant", "default")
    if not isinstance(tenant, str) or not tenant or len(tenant) > 64:
        raise ValueError(f"tenant {tenant!r} must be a non-empty "
                         "string of at most 64 chars")
    configs = out.get("configs")
    if not isinstance(configs, list) or not configs:
        raise ValueError("request needs a non-empty 'configs' list of "
                         "{mean, std} spec objects")
    specs = []
    for i, spec in enumerate(configs):
        if not isinstance(spec, dict):
            raise ValueError(f"configs[{i}] is not an object")
        clean = {}
        for key in ("mean", "std"):
            if key in spec:
                try:
                    clean[key] = float(spec[key])
                except (TypeError, ValueError):
                    raise ValueError(
                        f"configs[{i}].{key} is not a number: "
                        f"{spec[key]!r}") from None
        specs.append(clean)
    out["configs"] = specs
    proc = out.get("process")
    if proc is not None:
        # optional fault-process pin (fault/processes/ spec syntax):
        # the resident service trains ONE compiled process stack, so a
        # request naming a different one is refused at admission (the
        # service compares this string against its runner's canonical
        # spec) instead of silently training the wrong physics
        if not isinstance(proc, str) or not proc.strip() \
                or len(proc) > 256:
            raise ValueError(
                f"request process {proc!r} must be a non-empty "
                "fault-process spec string (at most 256 chars)")
        out["process"] = proc.strip()
    tiles = out.get("tiles")
    if tiles is not None:
        # optional tiled-crossbar-mapping pin (fault/mapping.py spec
        # syntax, e.g. "cells=256x256"): like the process pin, the
        # resident service trains ONE compiled tile mapping, so a
        # request naming a different one is refused at admission
        # (canonicalized comparison happens in the service — this
        # spool layer stays dependency-free)
        if not isinstance(tiles, str) or not tiles.strip() \
                or len(tiles) > 64:
            raise ValueError(
                f"request tiles {tiles!r} must be a non-empty tile-"
                "mapping spec string (at most 64 chars, e.g. '1x1' "
                "or 'cells=256x256')")
        out["tiles"] = tiles.strip()
    dp = out.get("dtype_policy")
    if dp is not None:
        # optional quantized-sweep-mode pin ("f32" | "ternary" |
        # "int8"): like the process pin, a resident lane pool compiles
        # ONE dtype policy, so a request naming a different one is
        # routed to a matching fleet worker (or hot-swaps one) rather
        # than silently served at the wrong precision. The legal-value
        # check happens at admission (the spool stays dependency-free).
        if not isinstance(dp, str) or not dp.strip() or len(dp) > 32:
            raise ValueError(
                f"request dtype_policy {dp!r} must be a non-empty "
                "string of at most 32 chars (e.g. 'f32', 'ternary')")
        out["dtype_policy"] = dp.strip()
    net = out.get("net")
    if net is not None:
        # optional net pin: the short name a fleet worker registered
        # its solver's net under — a request naming a different net is
        # routed/swapped, never silently trained on the wrong model
        if not isinstance(net, str) or not net.strip() \
                or len(net) > 128:
            raise ValueError(
                f"request net {net!r} must be a non-empty string of "
                "at most 128 chars (the worker-table net name)")
        out["net"] = net.strip()
    iters = out.get("iters") or default_iters
    if not iters:
        # no explicit budget and no default known HERE (e.g. the
        # client's durable spool fallback, which cannot see the
        # service's --default-iters): defer — the service re-validates
        # with its own default at pickup
        out.pop("iters", None)
    else:
        if not isinstance(iters, int) or isinstance(iters, bool) \
                or iters <= 0:
            raise ValueError(
                f"request iters must be a positive int, got "
                f"{out.get('iters')!r} (and the service has default "
                f"{default_iters})")
        out["iters"] = iters
    out.setdefault("submit_time", time.time())
    return out


def _atomic_write(path: str, payload: dict):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Spool:
    """The service-side view of the request queue (see module
    docstring). All mutation is rename-based and single-consumer: only
    the service moves files out of pending/."""

    def __init__(self, root: str):
        self.root = root
        for state in STATES:
            os.makedirs(os.path.join(root, state), exist_ok=True)

    def _dir(self, state: str) -> str:
        return os.path.join(self.root, state)

    def _path(self, state: str, request_id: str) -> str:
        return os.path.join(self._dir(state), f"{request_id}.json")

    def submit(self, request: dict, default_iters: int = 0) -> str:
        """Validate + atomically spool a request into pending/.
        Returns the request id. Duplicate ids are refused (a resubmit
        must pick a new id — the old one's lifecycle is already on
        disk)."""
        req = normalize_request(request, default_iters)
        rid = req["id"]
        if self.state_of(rid) is not None:
            raise ValueError(f"request id {rid!r} already exists in "
                             "the spool")
        _atomic_write(self._path("pending", rid), req)
        return rid

    def pending_ids(self) -> List[str]:
        """Pending request ids in processing (filename) order."""
        names = sorted(n for n in os.listdir(self._dir("pending"))
                       if n.endswith(".json"))
        return [n[:-len(".json")] for n in names]

    def state_of(self, request_id: str) -> Optional[str]:
        for state in STATES:
            if os.path.exists(self._path(state, request_id)):
                return state
        return None

    def read(self, request_id: str) -> Optional[dict]:
        """The request's current payload, from whichever state dir it
        lives in (None when unknown)."""
        for state in STATES:
            path = self._path(state, request_id)
            try:
                with open(path) as f:
                    return dict(json.load(f), state=state)
            except FileNotFoundError:
                continue
        return None

    def claim(self, request_id: str, updates: Optional[dict] = None
              ) -> dict:
        """pending -> active (admission). Returns the payload, with
        `updates` merged + persisted (e.g. the allocated config
        ids)."""
        return self._advance(request_id, "pending", "active", updates)

    def finish(self, request_id: str, updates: Optional[dict] = None,
               src: str = "active") -> dict:
        """active (or pending, for rejections) -> done, merging the
        terminal result payload into the file."""
        return self._advance(request_id, src, "done", updates)

    def _advance(self, request_id: str, src: str, dst: str,
                 updates: Optional[dict]) -> dict:
        path = self._path(src, request_id)
        with open(path) as f:
            req = json.load(f)
        if updates:
            req.update(updates)
        _atomic_write(self._path(dst, request_id), req)
        os.remove(path)
        return req

    def requeue(self, request_id: str,
                drop: tuple = ("cfg_ids", "iters_granted", "status",
                               "worker", "submit_seen")) -> dict:
        """active -> pending: put a claimed request back on the queue
        (the fleet controller's dead-worker path — at-least-once
        completion, lifted one level). The previous claimant's
        bookkeeping fields are dropped so the next pickup starts a
        fresh attempt; `submit_time` survives, so the request's
        terminal `latency_s` spans the WHOLE fleet turnaround
        including the failed attempt."""
        path = self._path("active", request_id)
        with open(path) as f:
            req = json.load(f)
        for key in drop:
            req.pop(key, None)
        req["requeues"] = int(req.get("requeues", 0)) + 1
        _atomic_write(self._path("pending", request_id), req)
        os.remove(path)
        return req

    def update(self, request_id: str, state: str, updates: dict
               ) -> dict:
        """Merge fields into a request file in place (no state move)."""
        path = self._path(state, request_id)
        with open(path) as f:
            req = json.load(f)
        req.update(updates)
        _atomic_write(path, req)
        return req

    def quarantine(self, request_id: str, reason: str) -> dict:
        """pending -> done for a file whose CONTENT cannot be parsed:
        the done/ payload is written fresh (the original bytes are
        junk) so the resident service never crashes — or spins — on a
        corrupt submission."""
        payload = {"id": request_id, "status": "rejected",
                   "reason": reason, "submit_time": time.time()}
        _atomic_write(self._path("done", request_id), payload)
        try:
            os.remove(self._path("pending", request_id))
        except FileNotFoundError:
            pass
        return payload

    def active(self) -> List[dict]:
        """Every active request payload, in filename order."""
        out = []
        for name in sorted(os.listdir(self._dir("active"))):
            if not name.endswith(".json"):
                continue
            with open(os.path.join(self._dir("active"), name)) as f:
                out.append(json.load(f))
        return out

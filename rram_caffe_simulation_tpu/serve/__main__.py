"""`python -m rram_caffe_simulation_tpu.serve` — run a sweep service."""
import sys

from .service import main

if __name__ == "__main__":
    sys.exit(main())

"""Weight initializers ("fillers") with Caffe-equivalent semantics.

Reference: include/caffe/filler.hpp:31-290 (ConstantFiller, UniformFiller,
GaussianFiller incl. sparse mode, PositiveUnitballFiller, XavierFiller,
MSRAFiller, BilinearFiller, GetFiller).

Each filler is a pure function of a jax PRNG key and a shape; fan_in/fan_out
follow Caffe's convention: for a blob of shape (d0, d1, ..., dn),
fan_in = count / d0 and fan_out = count / d1 (filler.hpp:136-160).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape) -> tuple[float, float]:
    count = int(np.prod(shape))
    fan_in = count / shape[0] if len(shape) >= 1 else count
    fan_out = count / shape[1] if len(shape) >= 2 else count
    return fan_in, fan_out


def _scale_n(filler, fan_in: float, fan_out: float) -> float:
    vn = filler.variance_norm
    from ..proto import pb
    if vn == pb.FillerParameter.AVERAGE:
        return (fan_in + fan_out) / 2.0
    if vn == pb.FillerParameter.FAN_OUT:
        return fan_out
    return fan_in


def make_filler(filler_param, dtype=jnp.float32):
    """Return fill(key, shape) -> array for a FillerParameter."""
    f = filler_param
    ftype = f.type

    if ftype == "constant":
        def fill(key, shape):
            return jnp.full(shape, f.value, dtype=dtype)
    elif ftype == "uniform":
        def fill(key, shape):
            return jax.random.uniform(key, shape, dtype=dtype,
                                      minval=f.min, maxval=f.max)
    elif ftype == "gaussian":
        def fill(key, shape):
            kg, ks = jax.random.split(key)
            x = f.mean + f.std * jax.random.normal(kg, shape, dtype=dtype)
            if f.sparse >= 0:
                # Bernoulli mask with p = sparse / fan_in keeps roughly
                # `sparse` nonzeros per output (filler.hpp:92-117).
                fan_in, _ = _fans(shape)
                p = min(1.0, f.sparse / max(fan_in, 1.0))
                mask = jax.random.bernoulli(ks, p, shape)
                x = jnp.where(mask, x, 0.0)
            return x
    elif ftype == "positive_unitball":
        def fill(key, shape):
            x = jax.random.uniform(key, shape, dtype=dtype)
            flat = x.reshape(shape[0], -1)
            flat = flat / jnp.sum(flat, axis=1, keepdims=True)
            return flat.reshape(shape)
    elif ftype == "xavier":
        def fill(key, shape):
            fan_in, fan_out = _fans(shape)
            scale = math.sqrt(3.0 / _scale_n(f, fan_in, fan_out))
            return jax.random.uniform(key, shape, dtype=dtype,
                                      minval=-scale, maxval=scale)
    elif ftype == "msra":
        def fill(key, shape):
            fan_in, fan_out = _fans(shape)
            std = math.sqrt(2.0 / _scale_n(f, fan_in, fan_out))
            return std * jax.random.normal(key, shape, dtype=dtype)
    elif ftype == "bilinear":
        def fill(key, shape):
            # Deterministic upsampling kernel (filler.hpp:213-246); blob must
            # be 4-D with square spatial dims.
            assert len(shape) == 4 and shape[2] == shape[3], \
                "bilinear filler needs a square 4-D blob"
            k = shape[3]
            fac = (k + 1) // 2
            center = fac - 1.0 if k % 2 == 1 else fac - 0.5
            coords = np.arange(k, dtype=np.float64)
            w1d = 1.0 - np.abs(coords - center) / fac
            w2d = np.outer(w1d, w1d)
            return jnp.broadcast_to(jnp.asarray(w2d, dtype=dtype), shape)
    else:
        raise ValueError(f"Unknown filler type: {ftype!r}")
    return fill

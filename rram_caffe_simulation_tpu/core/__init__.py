from .fillers import make_filler  # noqa: F401
from .registry import LAYER_REGISTRY, register_layer, create_layer  # noqa: F401

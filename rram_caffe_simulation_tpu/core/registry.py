"""Layer contract and registry.

Reference: include/caffe/layer.hpp:33 (Layer base: SetUp -> LayerSetUp/Reshape,
Forward/Backward dispatch, owned param blobs) and layer_factory.hpp:56-137
(LayerRegistry / REGISTER_LAYER_CLASS). The TPU design replaces the
CPU/GPU virtual-dispatch pair with a single pure `apply` traced by XLA;
`Backward` has no hand-written counterpart because `jax.grad` differentiates
`apply` directly. Engine selection (Caffe vs cuDNN, layer_factory.cpp:38-230)
collapses: every engine value lowers to the same XLA op.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax

LAYER_REGISTRY: dict[str, type] = {}


def register_layer(name: str) -> Callable[[type], type]:
    def wrap(cls: type) -> type:
        if name in LAYER_REGISTRY:
            raise KeyError(f"Layer type {name!r} registered twice")
        LAYER_REGISTRY[name] = cls
        cls.type_name = name
        return cls
    return wrap


def create_layer(layer_param, phase: int) -> "Layer":
    """String->layer creation (reference layer_factory.hpp:75 CreateLayer)."""
    t = layer_param.type
    if t not in LAYER_REGISTRY:
        raise KeyError(
            f"Unknown layer type {t!r} (layer {layer_param.name!r}); "
            f"registered: {sorted(LAYER_REGISTRY)}")
    return LAYER_REGISTRY[t](layer_param, phase)


# --- fault-process registry (fault/processes/) ------------------------
# The same string->class seam the layer registry gives the net builder,
# applied to time-dependent fault processes: a new fault physics model
# is a registration, not a solver edit (ROADMAP item 5's engine-choice
# seam, layer_factory.cpp:38 in the reference).

FAULT_PROCESS_REGISTRY: dict[str, type] = {}


def register_fault_process(name: str) -> Callable[[type], type]:
    def wrap(cls: type) -> type:
        if name in FAULT_PROCESS_REGISTRY:
            raise KeyError(f"Fault process {name!r} registered twice")
        FAULT_PROCESS_REGISTRY[name] = cls
        cls.process_name = name
        return cls
    return wrap


def create_fault_process(name: str, params: Optional[dict] = None):
    """String->process creation (the CreateLayer twin for fault
    physics). `params` is the process's free-form parameter dict from
    the FaultSpec."""
    if name not in FAULT_PROCESS_REGISTRY:
        raise KeyError(
            f"Unknown fault process {name!r}; registered: "
            f"{sorted(FAULT_PROCESS_REGISTRY)}")
    return FAULT_PROCESS_REGISTRY[name](params or {})


@dataclasses.dataclass
class LayerContext:
    """Trace-time context threaded through every layer apply.

    phase is static (it selects the traced branch, like Caffe's per-net
    Phase); rng is a traced PRNG key consumed by stochastic layers
    (Dropout, stochastic pooling, DummyData gaussian fillers).
    """
    phase: int  # pb.TRAIN or pb.TEST
    rng: Optional[jax.Array] = None
    # Net-level iteration counter, traced; used by BatchNorm moving averages.
    iteration: Optional[jax.Array] = None
    # Hardware-aware ADC model (RRAMForwardParameter.adc_bits, static):
    # when nonzero, crossbar (InnerProduct) layers quantize their output
    # with straight-through gradients (fault/hw_aware.quantize_ste).
    adc_bits: int = 0
    # Hardware-aware crossbar engine: maps fault-target layer name ->
    # (broken, stuck, seed, sigma, q_bits); the layer computes its
    # matmul through the fused fault/hw_aware.crossbar_matmul kernel.
    # Which hw_engine value populates this (and every fallback rule)
    # is documented ONCE: the ENGINE MATRIX in fault/hw_aware.py.
    crossbar: Optional[dict] = None
    # Tiled crossbar mapping (fault/mapping.py, static): maps a
    # fault-target layer name -> (tr, tc) tile cell dims — over the
    # STORED weight shape for InnerProduct layers, over the im2col
    # (C_in*kh*kw, C_out) weight VIEW for Convolution layers (ISSUE
    # 18). A listed layer computes its matmul as
    # per-tile ADC-quantized partial sums accumulated across the
    # K-tile axis (adc_bits per tile instead of one whole-output ADC)
    # — on the pure path via hw_aware.tiled_crossbar_matmul, on the
    # pallas path by folding the tile grid + ADC into the fused
    # kernel. Only multi-tile layers are listed; the default 1x1 spec
    # populates nothing and traces the untiled program.
    tiles: Optional[dict] = None
    # Mixed precision (Solver compute_dtype, static): layers that CREATE
    # float data inside the graph (DummyData fillers) emit it in this
    # dtype so generated blobs match the cast parameters.
    compute_dtype: Optional[Any] = None
    # Sequence parallelism (Solver.enable_sequence_parallel, static):
    # when a mesh is present, Attention layers run their core through
    # ring/ulysses attention sharded over seq_axis (parallel/sequence.py)
    # instead of the single-device path.
    seq_mesh: Optional[Any] = None
    seq_axis: str = "seq"
    seq_impl: str = "ring"
    # Conv im2col operand mode (ISSUE 19, static): how a TILED
    # Convolution layer builds its (M, K) patch GEMM operand —
    # "premat" (materialized once), "tilewise" (lazy per-K-tile slabs,
    # jax engine) or "implicit" (in-kernel / plan-driven gather from
    # the raw activation; the patch matrix never exists in HBM). None
    # defers to the RRAM_CONV_IM2COL env var, then "premat". The
    # solver resolves and records the effective mode
    # (`make_train_step(conv_im2col=)`); see ops/vision.py.
    conv_im2col: Optional[str] = None


@dataclasses.dataclass
class ParamSpec:
    """Learnable-parameter metadata (reference ParamSpec message + Net's
    AppendParam bookkeeping, net.cpp:451-540)."""
    name: str = ""
    lr_mult: float = 1.0
    decay_mult: float = 1.0


class Layer:
    """Base layer. Subclasses implement setup/init_params/apply.

    Lifecycle: __init__(layer_param, phase) stores config; setup(bottom_shapes)
    resolves static shape info and returns top shapes; init_params(key) draws
    initial parameter arrays; apply(params, bottoms, ctx) is the pure traced
    computation returning (tops, new_params_or_None). new_params carries
    forward-pass state updates (BatchNorm moving stats) — the functional
    replacement for Caffe layers mutating their own blobs_ during Forward.
    """

    type_name = "?"
    # Data-source layers produce tops from the host pipeline, not bottoms.
    is_data_source = False
    # Loss layers may omit `top:` in the prototxt; the net auto-names the
    # missing tops (reference layer.hpp AutoTopBlobs / net.cpp AppendTop
    # with a NULL layer_param).
    auto_top_blobs = False

    def __init__(self, layer_param, phase: int):
        self.lp = layer_param
        self.phase = phase
        self.name = layer_param.name
        self.top_shapes: list[tuple[int, ...]] = []

    # --- static setup ---------------------------------------------------
    def setup(self, bottom_shapes: Sequence[tuple[int, ...]]) -> list[tuple[int, ...]]:
        raise NotImplementedError

    def init_params(self, key) -> list[Any]:
        return []

    def param_specs(self) -> list[ParamSpec]:
        """One spec per param blob; pads/truncates lp.param like Caffe."""
        n = self.num_params()
        specs = []
        for i in range(n):
            if i < len(self.lp.param):
                p = self.lp.param[i]
                specs.append(ParamSpec(name=p.name, lr_mult=p.lr_mult,
                                       decay_mult=p.decay_mult))
            else:
                specs.append(ParamSpec())
        return specs

    def num_params(self) -> int:
        return 0

    # --- traced computation ---------------------------------------------
    def apply(self, params: Sequence[Any], bottoms: Sequence[Any],
              ctx: LayerContext):
        raise NotImplementedError

    # --- loss plumbing (reference layer.hpp:99 ExactNumTopBlobs etc.) ----
    def default_loss_weight(self, top_index: int) -> float:
        return 0.0

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"

"""Async execution layer: overlap host bookkeeping with device dispatch.

The sweep's device side sits at its bandwidth floor (RESULTS.md), so the
remaining wall-clock lever is the HOST: fetching losses/metrics at chunk
boundaries, feeding sinks, serializing snapshots, and setting up the next
resident group all stall the dispatch queue when they run inline. This
module holds the three host-side primitives the overlap is built from —
the production pattern of async-checkpointing / dispatch-pipelining
training stacks (Orbax, t5x; PAPERS.md):

- `OrderedConsumer`: a bounded-queue consumer thread that applies a
  callback to submitted items in EXACT submission order. The dispatcher
  enqueues chunk N+1 as soon as chunk N's donated-state handles return
  (JAX async dispatch) while the consumer drains completed chunks —
  device_get, sink writes, host strategy work — off the critical path.
  Errors are sticky like `data.feed.PrefetchingFeed`: the first call
  that observes a consumer failure re-raises it, and so does every later
  call (the thread stays alive and discards queued work, so nothing can
  block forever on a dead consumer).

- `BackgroundWriter`: serialize + atomic-rename file writes off-thread.
  Every payload is written to a sibling temp file and `os.replace`d into
  place only on success, so a crash mid-write can never leave a partial
  file under the final name (a good snapshot is never replaced by a bad
  one).

- `PipelineStats`: per-run accounting of where the host actually blocked
  (submit backpressure or inline consume), how long the consumer worked
  concurrently, snapshot write time moved off-loop, and overlapped
  group-setup seconds — assembled into the `pipeline` field of the
  observe `setup` record (observe/schema.py).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional


class StallError(RuntimeError):
    """The consumer thread stopped making progress while work was
    pending (its heartbeat went stale past the stall timeout): a sink
    blocked on a dead filesystem, a wedged device fetch — anything that
    would otherwise hang `submit`/`drain` forever. The sweep layer
    catches this to write a best-effort checkpoint before aborting
    instead of hanging the whole run; `checkpoint_path` carries that
    checkpoint's location when one was written."""

    def __init__(self, message: str, checkpoint_path: Optional[str] = None):
        super().__init__(message)
        self.checkpoint_path = checkpoint_path


class OrderedConsumer:
    """Bounded-queue consumer thread with in-order processing and sticky
    error propagation (the PrefetchingFeed pattern, consumer-side).

    `submit(item)` hands one unit of host work to the thread and returns
    the seconds it spent blocked (only when the queue — the pipeline
    depth — is full: that is backpressure, the dispatcher's true
    host-blocked time). `drain()` is the synchronous barrier: it returns
    once every submitted item has been consumed, re-raising any consumer
    error. After an error the thread keeps draining the queue WITHOUT
    processing, so neither submit nor drain can hang; every subsequent
    call re-raises the original failure."""

    def __init__(self, fn: Callable, depth: int = 2,
                 name: str = "chunk-consumer",
                 stall_timeout: Optional[float] = None):
        self._fn = fn
        self._depth = max(int(depth), 1)
        self._name = name
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.consumer_s = 0.0    # seconds the thread spent in fn
        #: optional observe.spans.SpanTracer: each consumed item
        #: becomes one `span_name` span on this (named) thread, so a
        #: Perfetto timeline shows the consumer's concurrency against
        #: the dispatcher. None = no tracing, zero overhead.
        self.tracer = None
        self.span_name = name
        # heartbeat: monotonic timestamp of the consumer's last sign of
        # life (item picked up or finished). With `stall_timeout` set, a
        # submit/drain that would block while the heartbeat is staler
        # than the timeout raises StallError instead of hanging.
        self.stall_timeout = stall_timeout
        self._beat = time.monotonic()

    def check(self):
        """Re-raise the sticky consumer error, if one has occurred."""
        if self._error is not None:
            raise self._error

    def idle_for(self) -> float:
        """Seconds since the consumer last made progress."""
        return time.monotonic() - self._beat

    def _check_stall(self, waited_from: float):
        """Raise StallError when the heartbeat is stale past the
        timeout AND the caller has itself been blocked at least that
        long (a freshly stale heartbeat with an instantly returning
        caller is not a stall)."""
        if self.stall_timeout is None:
            return
        if (self.idle_for() > self.stall_timeout
                and time.monotonic() - waited_from > self.stall_timeout):
            raise StallError(
                f"consumer {self._name!r} made no progress for "
                f"{self.idle_for():.1f}s (stall timeout "
                f"{self.stall_timeout:g}s) with work pending")

    def _run(self):
        while True:
            item = self._q.get()
            self._beat = time.monotonic()
            try:
                if item is _STOP:
                    return
                if self._error is None:
                    t0 = time.perf_counter()
                    self._fn(item)
                    dt = time.perf_counter() - t0
                    self.consumer_s += dt
                    if self.tracer is not None:
                        self.tracer.complete(self.span_name, dt,
                                             cat="host")
            except BaseException as e:   # surfaced at next submit/drain
                self._error = e
            finally:
                self._beat = time.monotonic()
                self._q.task_done()

    def submit(self, item) -> float:
        """Enqueue one item; returns seconds blocked on backpressure.
        Raises StallError when the queue is full and the consumer's
        heartbeat is stale past `stall_timeout`."""
        self.check()
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=self._name)
            self._thread.start()
        t0 = time.perf_counter()
        if self.stall_timeout is None:
            self._q.put(item)
        else:
            t_block = time.monotonic()
            while True:
                try:
                    self._q.put(item, timeout=min(
                        0.25, max(self.stall_timeout, 0.01)))
                    break
                except queue.Full:
                    self.check()
                    self._check_stall(t_block)
        return time.perf_counter() - t0

    def drain(self) -> float:
        """Barrier: block until every submitted item is consumed, then
        re-raise any sticky consumer error. Returns seconds blocked.
        Raises StallError when the heartbeat goes stale past
        `stall_timeout` while items are still pending."""
        self.check()
        t0 = time.perf_counter()
        if self.stall_timeout is None:
            self._q.join()
        else:
            t_block = time.monotonic()
            with self._q.all_tasks_done:
                while self._q.unfinished_tasks:
                    self._q.all_tasks_done.wait(min(
                        0.25, max(self.stall_timeout, 0.01)))
                    if self._q.unfinished_tasks:
                        if self._error is not None:
                            break   # sticky error drains the queue itself
                        self._check_stall(t_block)
        dt = time.perf_counter() - t0
        self.check()
        return dt

    def abandon(self):
        """Give up on a stalled consumer: mark it failed so no later
        call blocks on it again, and leave the (daemon) thread to die
        with the process. Used only on the stall-abort path — a healthy
        consumer is stopped with `close()`."""
        if self._error is None:
            self._error = StallError(
                f"consumer {self._name!r} abandoned after a stall")
        self._thread = None

    def close(self):
        """Stop the thread (pending items are still consumed first)."""
        if self._thread is not None and self._thread.is_alive():
            self._q.put(_STOP)
            self._thread.join()
        self._thread = None


_STOP = object()


def atomic_write(path: str, write_fn: Callable[[str], None]):
    """Run `write_fn(tmp_path)` against a sibling temp file and
    `os.replace` it into `path` only on success; the temp file is
    removed on failure so a crash mid-serialization never leaves a
    partial file under the final name."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


class BackgroundWriter:
    """Off-thread snapshot/fault-state writer: the hot loop pays only the
    device_get (materializing the trees), then hands (path, write_fn) to
    this writer, which serializes to a temp file and atomically renames.
    `wait()` is the barrier; errors are sticky via OrderedConsumer."""

    def __init__(self, depth: int = 2):
        self._consumer = OrderedConsumer(self._write, depth=depth,
                                         name="snapshot-writer")
        self._consumer.span_name = "write"
        self.write_s = 0.0       # total off-loop serialize+write seconds

    @property
    def tracer(self):
        """Optional SpanTracer: each queued write becomes one "write"
        span on the snapshot-writer thread."""
        return self._consumer.tracer

    @tracer.setter
    def tracer(self, tracer):
        self._consumer.tracer = tracer

    def _write(self, item):
        path, write_fn = item
        t0 = time.perf_counter()
        atomic_write(path, write_fn)
        self.write_s += time.perf_counter() - t0

    def submit(self, path: str, write_fn: Callable[[str], None]):
        """Queue one atomic file write; `write_fn(tmp_path)` runs on the
        writer thread. Re-raises a prior writer error (sticky)."""
        self._consumer.submit((path, write_fn))

    def wait(self):
        """Block until all queued writes have landed (or re-raise the
        first writer error)."""
        self._consumer.drain()

    def close(self):
        self._consumer.close()


class PipelineStats:
    """Host-overlap accounting for one runner/run, assembled into the
    `pipeline` field of the observe `setup` record (schema.py). In sync
    mode `host_blocked_s` is the inline fetch+sink time per chunk; in
    pipelined mode it is submit backpressure only — the acceptance
    signal is the pipelined value falling strictly below the sync one
    for the same work."""

    def __init__(self, depth: int = 0):
        self.depth = int(depth)
        self.chunks = 0
        self.records = 0
        self.host_blocked_s = 0.0
        self.consumer_s = 0.0
        self.drain_s = 0.0
        self.snapshot_write_s = 0.0
        self.checkpoint_write_s = 0.0
        self.setup_overlap_s = 0.0

    def record(self) -> dict:
        """The `pipeline` sub-record (observe/schema.py PIPELINE_FIELDS)."""
        rec = {
            "depth": self.depth,
            "chunks": int(self.chunks),
            "host_blocked_seconds": round(float(self.host_blocked_s), 6),
        }
        if self.records:
            rec["records"] = int(self.records)
        if self.consumer_s:
            rec["consumer_seconds"] = round(float(self.consumer_s), 6)
        if self.drain_s:
            rec["drain_seconds"] = round(float(self.drain_s), 6)
        if self.snapshot_write_s:
            rec["snapshot_write_seconds"] = round(
                float(self.snapshot_write_s), 6)
        if self.checkpoint_write_s:
            # inline sweep-checkpoint writes (SweepRunner.checkpoint
            # with background=False) — the durability layer's per-group
            # overhead, tracked so RESULTS.md can report it
            rec["checkpoint_write_seconds"] = round(
                float(self.checkpoint_write_s), 6)
        if self.setup_overlap_s:
            rec["setup_overlap_seconds"] = round(
                float(self.setup_overlap_s), 6)
        return rec

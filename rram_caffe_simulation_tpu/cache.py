"""Cold-start performance layer: persistent compile cache + setup stats.

BENCH_r05 measured 136.6 s of per-process setup (LMDB decode + XLA
compilation) against a ~12 s steady-state train loop — fault-tolerance
studies are Monte-Carlo by construction (many short runs over fault
configs), so that setup tax recurs on every process start and caps
`fault_configs_swept_per_hour` directly. This module is the wiring that
makes the second and every later run start warm:

- `enable_compilation_cache` points JAX's persistent compilation cache
  (`jax_compilation_cache_dir`) at `<cache_dir>/xla`, so every jitted
  step function — Solver, SweepRunner, the dp/tp/pp wrappers — hits
  disk instead of recompiling. Controlled by the `RRAM_TPU_CACHE_DIR`
  env var and the `caffe_cli --cache-dir` / bench `--cache-dir` flags;
  with neither set, nothing changes.
- hit/miss counters ride JAX's monitoring events, so the emitted
  `setup` record (observe/schema.py) can say whether a run's compiles
  came from disk ("hit"), were compiled fresh ("miss"), or mixed
  ("partial").
- `SetupStats` collects the cold-start phase timings (decode seconds,
  compile seconds, per-cache hit/miss) and assembles the structured
  `setup` record benches and the sweep runner emit.

The decoded-dataset half of the layer lives in `data/dataset_cache.py`
(same root directory, `<cache_dir>/datasets`).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

ENV_VAR = "RRAM_TPU_CACHE_DIR"

_lock = threading.Lock()
_state = {"dir": None, "explicit": False, "listener": False}
_counts = {"hits": 0, "misses": 0}


def resolve_cache_dir(cli_value: Optional[str] = None) -> Optional[str]:
    """The cache root: explicit argument (CLI flag) wins, then the
    RRAM_TPU_CACHE_DIR env var; None = caching disabled."""
    if cli_value:
        return os.path.abspath(os.path.expanduser(cli_value))
    env = os.environ.get(ENV_VAR, "")
    return os.path.abspath(os.path.expanduser(env)) if env else None


def _on_event(name: str, **kw):
    # JAX emits these from the persistent-cache lookup path
    # (jax/_src/compiler.py); counting them is how the setup record
    # knows hit vs miss without touching cache internals.
    if name == "/jax/compilation_cache/cache_hits":
        _counts["hits"] += 1
    elif name == "/jax/compilation_cache/cache_misses":
        _counts["misses"] += 1


def enable_compilation_cache(cache_dir: Optional[str] = None,
                             min_compile_time_s: Optional[float] = None,
                             ) -> Optional[str]:
    """Wire the persistent XLA compilation cache to
    `<cache_dir>/xla` (cache_dir resolved via `resolve_cache_dir`).
    Returns the cache root, or None when no directory is configured —
    in which case this is a no-op and compiles stay in-memory-only.

    Idempotent; safe to call from every entry point (Solver.__init__,
    the CLI, benches). An EXPLICIT directory (CLI flag) is latched:
    later bare calls — e.g. Solver.__init__'s env-var hook — keep it
    rather than demoting to the env var, so `--cache-dir` wins for the
    whole process as its help text promises. By default the
    min-compile-time/size thresholds are zeroed so even
    millisecond-scale step functions (tiny CI nets) persist — the
    whole point is that NO second compile of the same program ever
    happens on this machine. An EXPLICIT `min_compile_time_s` is
    latched like the directory (later bare calls keep it): fleet
    workers pass 0.05 s to keep eager tiny-op executables OUT of the
    cache, because deserializing the swarm of sub-millisecond
    eager-primitive entries the zeroed threshold admits intermittently
    SEGFAULTS on this jaxlib (faulthandler pinned it to
    apply_primitive on a convert_element_type hit; the fleet guard's
    swap machinery found it). The chunk executables that matter for
    the hot-swap-as-cache-hit contract compile far above 0.05 s, and
    eager ops recompile fresh in microseconds."""
    if not cache_dir and _state["explicit"] and _state["dir"]:
        if min_compile_time_s is not None:
            # the dir is latched but an explicit threshold still
            # applies — dropping it here would silently re-admit the
            # eager tiny-op entries the caller is guarding against
            with _lock:
                _state["min_compile_time_s"] = float(min_compile_time_s)
            import jax
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(min_compile_time_s))
        return _state["dir"]
    d = resolve_cache_dir(cache_dir)
    if d is None:
        return None
    import jax
    xla_dir = os.path.join(d, "xla")
    os.makedirs(xla_dir, exist_ok=True)
    with _lock:
        changed = _state["dir"] != d
        if min_compile_time_s is not None:
            # explicit threshold latches, like the explicit dir — a
            # later bare call (Solver.__init__) must not demote a
            # fleet worker's 0.05 s back to the zeroed default
            _state["min_compile_time_s"] = float(min_compile_time_s)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          _state.get("min_compile_time_s", 0.0))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        if changed:
            # JAX latches its cache-in-use decision at the FIRST compile
            # of the process; enabling after any jit has run is silently
            # ignored unless that latch is reset (the on-disk content is
            # untouched — this only re-arms the lookup path).
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        if not _state["listener"]:
            from jax._src import monitoring
            monitoring.register_event_listener(_on_event)
            _state["listener"] = True
        _state["dir"] = d
        if cache_dir:
            _state["explicit"] = True
    return d


def cache_dir() -> Optional[str]:
    """The active cache root (None until enable_compilation_cache
    succeeds)."""
    return _state["dir"]


def clone_cache(src_root: str, dst_root: str) -> int:
    """Snapshot a warm cache root into a PRIVATE one by hard-linking
    every completed entry (`xla/` executables + `datasets/` decoded
    arrays). Returns the number of entries linked.

    Why this exists: N live jax processes sharing ONE persistent
    compilation cache is unsafe — concurrent compile/deserialize
    activity against the shared directory intermittently yields
    corrupt executables (observed on the CPU backend as garbage
    numerics, SIGSEGV, and glibc heap-corruption aborts; the fleet
    guard's isolation bisect pinned it: 3/3 clean without the shared
    cache, 3/3 corrupt with it). A fleet worker therefore snapshots
    the shared warm cache at startup and points jax at its own copy:
    hits (and the hot-swap-as-cache-hit contract) survive, while no
    two live processes ever touch the same cache files. Hard links
    make the snapshot O(entries) metadata work — entries are
    immutable and writers replace via temp-file + rename, which
    breaks links instead of mutating shared bytes. In-flight temp
    files are skipped."""
    linked = 0
    for sub in ("xla", "datasets"):
        src = os.path.join(src_root, sub)
        if not os.path.isdir(src):
            continue
        for dirpath, _dirs, files in os.walk(src):
            rel = os.path.relpath(dirpath, src)
            dst_dir = os.path.join(dst_root, sub,
                                   "" if rel == "." else rel)
            os.makedirs(dst_dir, exist_ok=True)
            for name in files:
                if ".tmp" in name:
                    continue   # a writer mid-flight; not an entry yet
                dst = os.path.join(dst_dir, name)
                if os.path.exists(dst):
                    continue
                try:
                    os.link(os.path.join(dirpath, name), dst)
                except OSError:
                    # cross-device or link-unfriendly fs: copy instead
                    import shutil
                    shutil.copy2(os.path.join(dirpath, name), dst)
                linked += 1
    return linked


def compile_cache_stats() -> dict:
    """Cumulative persistent-cache counters for this process:
    {"hits": int, "misses": int}."""
    return dict(_counts)


def _status_from(h0: int, m0: int) -> str:
    """hit / miss / partial / disabled from a counter delta."""
    if _state["dir"] is None:
        return "disabled"
    dh = _counts["hits"] - h0
    dm = _counts["misses"] - m0
    if dh and not dm:
        return "hit"
    if dh and dm:
        return "partial"
    return "miss"


class SetupStats:
    """Cold-start phase accounting for one process: decode seconds,
    compile seconds, and per-cache hit/miss, assembled into the
    `setup` record documented in observe/schema.py.

    Compile status is derived from the persistent-cache counter delta
    over this object's lifetime, so construct it BEFORE the first
    compile of the run."""

    def __init__(self):
        self.decode_s = 0.0
        self.compile_s = 0.0
        self.dataset = "disabled"   # hit | miss | disabled
        # async-execution-layer accounting (async_exec.PipelineStats),
        # attached by the runner when the dispatch pipeline is on
        self.pipeline = None
        # HBM-floor accounting (ISSUE 7): the runner's estimated bytes
        # per sweep iteration and the fault-state format behind it
        # (SweepRunner.bytes_per_step_est; "f32" | "packed")
        self.bytes_per_step = None
        self.fault_format = None
        # pod-scale accounting (ISSUE 9): how many shards the config
        # axis is laid over (1 = single chip; bytes_per_step is the
        # PER-CHIP resident share under the mesh)
        self.config_shards = None
        # loud-fallback accounting (ISSUE 13): why an engine="pallas"
        # request resolved to the jax engine (None = no fallback)
        self.engine_fallback_reason = None
        # fault-physics accounting (ISSUE 10): the process stack +
        # explicit params this run trains under (FaultSpec.to_model —
        # {"spec": canonical, "processes": {...}})
        self.fault_model = None
        # tiled-mapping coverage (ISSUE 17): fault-target layers a
        # non-default tile spec did NOT cover (conv layers bypass the
        # crossbar tiling; Solver.tiles_bypassed) — None/[] = full
        # coverage
        self.tiles_bypassed = None
        # conv im2col operand mode (ISSUE 19): the RESOLVED mode a
        # tiled-conv sweep traced (premat | tilewise | implicit; None =
        # no tiled conv layer), the fallback/engagement reason, and the
        # patch-operand share of bytes_per_step
        # (SweepRunner.conv_patch_bytes_est)
        self.conv_im2col = None
        self.conv_im2col_reason = None
        self.conv_patch_bytes = None
        self._h0 = _counts["hits"]
        self._m0 = _counts["misses"]

    def add_decode(self, seconds: float):
        self.decode_s += float(seconds)

    def add_compile(self, seconds: float):
        self.compile_s += float(seconds)

    def timed_decode(self):
        return _Timed(self.add_decode)

    def timed_compile(self):
        return _Timed(self.add_compile)

    def compile_status(self) -> str:
        return _status_from(self._h0, self._m0)

    def record(self, setup_s: Optional[float] = None) -> dict:
        """The schema-versioned `setup` record (observe/schema.py);
        `setup_s` is the caller's total wall clock when it tracked one
        (decode and compile may overlap, so the phases need not sum to
        it)."""
        from .observe.sink import make_setup_record
        return make_setup_record(
            decode_s=self.decode_s, compile_s=self.compile_s,
            compile_status=self.compile_status(),
            dataset_status=self.dataset,
            cache_dir=_state["dir"], setup_s=setup_s,
            pipeline=(self.pipeline.record()
                      if self.pipeline is not None else None),
            bytes_per_step_est=self.bytes_per_step,
            fault_state_format=self.fault_format,
            config_shards=self.config_shards,
            fault_model=self.fault_model,
            engine_fallback_reason=self.engine_fallback_reason,
            tiles_bypassed=self.tiles_bypassed,
            conv_im2col=self.conv_im2col,
            conv_im2col_reason=self.conv_im2col_reason,
            conv_patch_bytes=self.conv_patch_bytes)


class _Timed:
    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._sink(time.perf_counter() - self._t0)
        return False

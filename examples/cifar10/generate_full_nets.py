"""Generate the CIFAR-10 "full" family (reference examples/cifar10/):
cifar10_full (ReLU + WITHIN_CHANNEL LRN), the sigmoid variant, and the
sigmoid+BatchNorm variant, plus their solvers — the nets the reference
ships beyond quick. Sources point at the in-repo sample LMDBs; ~81%
(full) needs the complete 60k-image set (reference examples/cifar10/
readme.md).

Run:  python examples/cifar10/generate_full_nets.py
"""
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)

from rram_caffe_simulation_tpu.api.net_spec import NetSpec, layers as L, params as P  # noqa: E402
from rram_caffe_simulation_tpu.proto import pb  # noqa: E402

WEIGHT_PARAM = [dict(lr_mult=1), dict(lr_mult=2)]
BN_PARAM = [dict(lr_mult=0)] * 3  # moving mean/var/scale-bias are not learned


def data_layers(proto_name):
    """TRAIN + TEST Data layers over the in-repo sample LMDBs."""
    out = []
    for phase, split in ((pb.TRAIN, "train"), (pb.TEST, "test")):
        lp = pb.LayerParameter()
        lp.name = "cifar"
        lp.type = "Data"
        lp.top.extend(["data", "label"])
        lp.include.add().phase = phase
        lp.transform_param.mean_file = "examples/cifar10/mean.binaryproto"
        lp.data_param.source = f"examples/cifar10/cifar10_{split}_lmdb"
        lp.data_param.batch_size = 100
        lp.data_param.backend = pb.DataParameter.LMDB
        out.append(lp)
    return out


def conv(n, name, bottom, std):
    n[name] = L.Convolution(
        bottom, num_output=32 if name != "conv3" else 64, pad=2,
        kernel_size=5, stride=1, param=WEIGHT_PARAM,
        weight_filler=dict(type="gaussian", std=std),
        bias_filler=dict(type="constant"))
    return n[name]


def head(n, bottom):
    n.ip1 = L.InnerProduct(
        bottom, num_output=10,
        param=[dict(lr_mult=1, decay_mult=250), dict(lr_mult=2, decay_mult=0)],
        weight_filler=dict(type="gaussian", std=0.01),
        bias_filler=dict(type="constant"))
    n.accuracy = L.Accuracy(n.ip1, n.label, include=dict(phase=pb.TEST))
    n.loss = L.SoftmaxWithLoss(n.ip1, n.label)


def full_net():
    """conv-pool-relu-LRN x2 (WITHIN_CHANNEL) + conv-relu-pool + ip."""
    n = NetSpec()
    n.data, n.label = L.Input(
        ntop=2, name="cifar",
        input_param=dict(shape=[dict(dim=[100, 3, 32, 32]),
                                dict(dim=[100])]))
    conv(n, "conv1", n.data, 0.0001)
    n.pool1 = L.Pooling(n.conv1, pool=P.Pooling.MAX, kernel_size=3, stride=2)
    n.relu1 = L.ReLU(n.pool1, in_place=True)
    n.norm1 = L.LRN(n.pool1, local_size=3, alpha=5e-5, beta=0.75,
                    norm_region=P.LRN.WITHIN_CHANNEL)
    conv(n, "conv2", n.norm1, 0.01)
    n.relu2 = L.ReLU(n.conv2, in_place=True)
    n.pool2 = L.Pooling(n.conv2, pool=P.Pooling.AVE, kernel_size=3, stride=2)
    n.norm2 = L.LRN(n.pool2, local_size=3, alpha=5e-5, beta=0.75,
                    norm_region=P.LRN.WITHIN_CHANNEL)
    conv(n, "conv3", n.norm2, 0.01)
    n.relu3 = L.ReLU(n.conv3, in_place=True)
    n.pool3 = L.Pooling(n.conv3, pool=P.Pooling.AVE, kernel_size=3, stride=2)
    head(n, n.pool3)
    return finish(n, "CIFAR10_full")


def sigmoid_net(with_bn):
    """conv-pool-[bn]-sigmoid stacks (the BN ablation pair the reference
    ships to show sigmoid nets only train with normalization)."""
    n = NetSpec()
    n.data, n.label = L.Input(
        ntop=2, name="cifar",
        input_param=dict(shape=[dict(dim=[100, 3, 32, 32]),
                                dict(dim=[100])]))
    conv(n, "conv1", n.data, 0.0001)
    n.pool1 = L.Pooling(n.conv1, pool=P.Pooling.MAX, kernel_size=3, stride=2)
    act1_in = n.pool1
    if with_bn:
        n.bn1 = L.BatchNorm(n.pool1, param=BN_PARAM)
        act1_in = n.bn1
    n.Sigmoid1 = L.Sigmoid(act1_in, in_place=True)
    conv(n, "conv2", act1_in, 0.01)
    act2_in = n.conv2
    if with_bn:
        n.bn2 = L.BatchNorm(n.conv2, param=BN_PARAM)
        act2_in = n.bn2
    n.Sigmoid2 = L.Sigmoid(act2_in, in_place=True)
    n.pool2 = L.Pooling(act2_in, pool=P.Pooling.AVE, kernel_size=3, stride=2)
    conv(n, "conv3", n.pool2, 0.01)
    act3_in = n.conv3
    if with_bn:
        n.bn3 = L.BatchNorm(n.conv3, param=BN_PARAM)
        act3_in = n.bn3
    n.Sigmoid3 = L.Sigmoid(act3_in, in_place=True)
    n.pool3 = L.Pooling(act3_in, pool=P.Pooling.AVE, kernel_size=3, stride=2)
    head(n, n.pool3)
    return finish(n, "CIFAR10_full_sigmoid" + ("_bn" if with_bn else ""))


def finish(n, name):
    proto = n.to_proto()
    proto.name = name
    # swap the Input scaffold for the TRAIN/TEST Data layer pair
    del proto.layer[0]
    for lp in reversed(data_layers(name)):
        proto.layer.insert(0, lp)
    return proto


def solver(net_file, prefix, base_lr=0.001, max_iter=60000, momentum=0.9):
    return f"""\
net: "examples/cifar10/{net_file}"
test_iter: 100
test_interval: 1000
base_lr: {base_lr}
momentum: {momentum}
weight_decay: 0.004
lr_policy: "fixed"
display: 200
max_iter: {max_iter}
snapshot: 10000
snapshot_format: HDF5
snapshot_prefix: "examples/cifar10/{prefix}"
"""


def main():
    out = {
        "cifar10_full_train_test.prototxt": str(full_net()),
        "cifar10_full_sigmoid_train_test.prototxt": str(sigmoid_net(False)),
        "cifar10_full_sigmoid_train_test_bn.prototxt": str(sigmoid_net(True)),
        "cifar10_full_solver.prototxt":
            solver("cifar10_full_train_test.prototxt", "cifar10_full"),
        # the two continuation solvers of the reference's 3-stage schedule
        "cifar10_full_solver_lr1.prototxt":
            solver("cifar10_full_train_test.prototxt", "cifar10_full",
                   base_lr=0.0001, max_iter=65000),
        "cifar10_full_solver_lr2.prototxt":
            solver("cifar10_full_train_test.prototxt", "cifar10_full",
                   base_lr=0.00001, max_iter=70000),
        "cifar10_full_sigmoid_solver.prototxt":
            solver("cifar10_full_sigmoid_train_test.prototxt",
                   "cifar10_full_sigmoid"),
        "cifar10_full_sigmoid_solver_bn.prototxt":
            solver("cifar10_full_sigmoid_train_test_bn.prototxt",
                   "cifar10_full_sigmoid_bn"),
    }
    for fname, text in out.items():
        with open(os.path.join(HERE, fname), "w") as f:
            f.write(text)
    print("wrote", ", ".join(sorted(out)))


if __name__ == "__main__":
    main()

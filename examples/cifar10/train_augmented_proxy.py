"""Generalization evidence for cifar10_quick without the full dataset
(VERDICT r2 item 7a): the in-repo sample LMDBs hold 200 real CIFAR-10
training images and 100 real, DISJOINT test images — far too few for
the reference's 75% contract, but enough to show a non-chance
generalization curve once the training sample is augmented
(mirror + pad-4 random crop + brightness jitter, the standard CIFAR
recipe). Chance is 10%; anything well above it on the 100 held-out real
images proves the training stack learns transferable features from real
data end-to-end (converter -> LMDB -> transformer -> solver).

    python examples/cifar10/train_augmented_proxy.py \
        [--aug 24] [--iters 3000] [--out DIR]
"""
import argparse
import os
import sys
import tempfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)


def load_lmdb(path):
    from rram_caffe_simulation_tpu.data.db import LMDB, datum_to_array
    from rram_caffe_simulation_tpu.proto import pb
    db = LMDB(path)
    xs, ys = [], []
    for _, v in db.env.items():
        d = pb.Datum()
        d.ParseFromString(v)
        arr, label = datum_to_array(d)
        xs.append(arr)
        ys.append(label)
    db.close()
    return np.stack(xs), np.asarray(ys)


def augment(x, rng):
    """One augmented view of a (3,32,32) uint8 image."""
    img = x.astype(np.int16)
    if rng.rand() < 0.5:
        img = img[:, :, ::-1]                       # mirror
    pad = np.pad(img, ((0, 0), (4, 4), (4, 4)), mode="reflect")
    oy, ox = rng.randint(0, 9, size=2)
    img = pad[:, oy:oy + 32, ox:ox + 32]            # random 32-crop
    img = img + rng.randint(-20, 21)                # brightness
    scale = 1.0 + 0.2 * (rng.rand() - 0.5)          # contrast
    img = (img - img.mean()) * scale + img.mean()
    # (8x8 cutout was tried and HURT at this tiny scale: peak 0.15 vs
    # 0.17 without — 200 unique images need the model to see whole
    # objects more than it needs occlusion robustness)
    return np.clip(img, 0, 255).astype(np.uint8)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--aug", type=int, default=24,
                   help="augmented copies per training image")
    p.add_argument("--iters", type=int, default=3000)
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--out", default="",
                   help="workdir (default: temp dir)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    os.chdir(REPO)
    from rram_caffe_simulation_tpu.data.db import array_to_datum
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.tools.converters import (
        _bulk_writer, compute_image_mean)
    from rram_caffe_simulation_tpu.utils.io import (read_net_param,
                                                    write_proto_text)

    work = args.out or tempfile.mkdtemp(prefix="cifar_aug_")
    os.makedirs(work, exist_ok=True)
    xs, ys = load_lmdb("examples/cifar10/cifar10_train_lmdb")
    print(f"augmenting {len(xs)} real CIFAR images x{args.aug}",
          flush=True)
    rng = np.random.RandomState(args.seed)
    aug_dir = os.path.join(work, "aug_lmdb")
    order = rng.permutation(len(xs) * args.aug)
    with _bulk_writer(aug_dir) as w:
        for j, idx in enumerate(order):
            src = idx % len(xs)
            img = augment(xs[src], rng)
            w.put(f"{j:08d}".encode(),
                  array_to_datum(img, int(ys[src])).SerializeToString())
    mean_file = os.path.join(work, "mean.binaryproto")
    compute_image_mean(aug_dir, mean_file)

    npar = read_net_param(
        "models/cifar10_quick/cifar10_quick_lmdb_train_test.prototxt")
    for lp in npar.layer:
        if lp.type == "Data":
            lp.transform_param.mean_file = mean_file
            phases = [i.phase for i in lp.include]
            if pb.TRAIN in phases:
                lp.data_param.source = aug_dir
                lp.data_param.batch_size = args.batch
            else:
                lp.data_param.source = "examples/cifar10/cifar10_test_lmdb"
                lp.data_param.batch_size = 100
    net_path = os.path.join(work, "train_val.prototxt")
    write_proto_text(net_path, npar)

    sp = pb.SolverParameter()
    sp.net = net_path
    # quick-recipe lr with stronger decay: 200 unique images overfit
    # fast, so the evidence is the held-out CURVE (evaluated every
    # `eval_every` iters), not the final point
    sp.base_lr = 0.001
    sp.lr_policy = "step"
    sp.gamma = 0.1
    sp.stepsize = max(args.iters * 3 // 4, 1)
    sp.momentum = 0.9
    sp.weight_decay = 0.02
    sp.display = 0
    sp.ClearField("test_interval")
    sp.test_iter.append(1)       # the whole 100-image test set
    sp.max_iter = args.iters
    sp.random_seed = 1
    sp.snapshot_prefix = os.path.join(work, "quick_aug")
    solver = Solver(sp)
    eval_every = max(args.iters // 16, 1)
    curve = []
    while solver.iter < args.iters:
        n = min(eval_every, args.iters - solver.iter)
        solver.step_fused(n, chunk=n)
        acc = solver.test(0).get("accuracy", 0.0)
        curve.append((solver.iter, acc))
        print(f"iter {solver.iter}: held-out accuracy {acc:.3f}",
              flush=True)
    best_iter, best = max(curve, key=lambda t: t[1])
    final = curve[-1][1]
    print(f"held-out accuracy on 100 real CIFAR test images: "
          f"best {best:.3f} @ iter {best_iter}, final {final:.3f} "
          f"(chance 0.100)", flush=True)
    return best


if __name__ == "__main__":
    main()

"""Build an MNIST-style dataset in idx format + LMDB without network access.

The real MNIST files are not shipped in this image (the reference fetches
them with data/mnist/get_mnist.sh, which needs the network), so this uses
scikit-learn's bundled `load_digits` corpus — 1,797 real handwritten digit
images — upscaled from 8x8 to the 28x28 LeNet geometry and augmented with
small integer shifts. The images are written as idx files and then pushed
through the framework's own MNIST converter (tools/converters.py
convert_mnist, parity with reference examples/mnist/convert_mnist_data.cpp)
so the full converter -> LMDB -> Data-layer path is exercised.

Usage: python examples/mnist/make_digits_dataset.py [out_dir]
"""
import os
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def write_idx(path: str, arr: np.ndarray) -> None:
    """Inverse of tools/converters.py read_idx."""
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x0800 | arr.ndim))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(arr.tobytes())


def upscale_28(img8: np.ndarray) -> np.ndarray:
    """8x8 (0..16) -> 28x28 (0..255) by 3x nearest-neighbour + 2px border."""
    big = np.kron(img8, np.ones((3, 3)))          # 24x24
    out = np.zeros((28, 28))
    out[2:26, 2:26] = big
    return np.clip(out * (255.0 / 16.0), 0, 255).astype(np.uint8)


def build(out_dir: str, shifts: int = 4, seed: int = 0):
    from sklearn.datasets import load_digits
    d = load_digits()
    rng = np.random.RandomState(seed)
    n = len(d.images)
    order = rng.permutation(n)
    split = int(n * 0.85)
    tr_idx, te_idx = order[:split], order[split:]

    def render(idx, augment):
        imgs, labels = [], []
        for i in idx:
            base = upscale_28(d.images[i])
            imgs.append(base)
            labels.append(d.target[i])
            for _ in range(shifts if augment else 0):
                dy, dx = rng.randint(-2, 3, size=2)
                imgs.append(np.roll(np.roll(base, dy, 0), dx, 1))
                labels.append(d.target[i])
        return np.stack(imgs), np.asarray(labels, np.uint8)

    os.makedirs(out_dir, exist_ok=True)
    tr_imgs, tr_labels = render(tr_idx, augment=True)
    te_imgs, te_labels = render(te_idx, augment=False)
    # shuffle the augmented training set so LMDB order is not class-banded
    perm = rng.permutation(len(tr_imgs))
    tr_imgs, tr_labels = tr_imgs[perm], tr_labels[perm]
    paths = {}
    for name, arr in (("train-images-idx3", tr_imgs),
                      ("train-labels-idx1", tr_labels),
                      ("t10k-images-idx3", te_imgs),
                      ("t10k-labels-idx1", te_labels)):
        paths[name] = os.path.join(out_dir, f"{name}-ubyte")
        write_idx(paths[name], arr)

    from rram_caffe_simulation_tpu.tools.converters import convert_mnist
    n_tr = convert_mnist(paths["train-images-idx3"], paths["train-labels-idx1"],
                         os.path.join(out_dir, "digits_train_lmdb"))
    n_te = convert_mnist(paths["t10k-images-idx3"], paths["t10k-labels-idx1"],
                         os.path.join(out_dir, "digits_test_lmdb"))
    print(f"digits dataset: {n_tr} train / {n_te} test images -> {out_dir}")
    return n_tr, n_te


if __name__ == "__main__":
    build(sys.argv[1] if len(sys.argv) > 1
          else os.path.dirname(os.path.abspath(__file__)))

"""Classification web demo on the Python standard library.

Reference: examples/web_demo/app.py (a Flask+Tornado app serving the
pycaffe Classifier with an upload form and a URL field; readme.md lists
flask/tornado/pillow in requirements.txt). This image ships no flask, so
the same surface is rebuilt on `http.server`:

  GET  /                 the demo page (URL field + file upload form)
  GET  /classify_url?imageurl=...    fetch and classify an image URL
  POST /classify_upload  classify an uploaded image (multipart form)

Results render as the reference's table of the top-5 (label,
probability) pairs with the classified image embedded base64 in the
page, and classification errors come back as a friendly banner rather
than a stack trace. The reference's "maximally accurate / maximally
specific" second table needs its ImageNet bet pickle (not shipped and
not derivable) and is omitted.

Run:
  python examples/web_demo/app.py --model-def models/.../deploy.prototxt \
      --pretrained-model weights.caffemodel --labels labels.txt --port 5000
"""
from __future__ import annotations

import argparse
import base64
import html
import http.client
import io
import ipaddress
import os
import socket
import ssl
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, REPO)

ALLOWED_EXT = {"png", "jpg", "jpeg", "bmp", "gif"}

MAX_FETCH_BYTES = 10 * 1024 * 1024
# tests / local dev only (--allow-private-urls): permit loopback targets
ALLOW_PRIVATE = False


def _host_is_public(hostname) -> bool:
    """Every address the name resolves to must be globally routable —
    otherwise the demo is an SSRF proxy into the host's network
    (cloud metadata at 169.254.169.254, intranet services, localhost)."""
    try:
        _resolve_pinned(hostname)
        return True
    except ValueError:
        return False


def _resolve_pinned(hostname) -> str:
    """Resolve ONCE, validate every returned address, and return the one
    IP the connection will actually use — connecting by name would let a
    TTL-0 DNS rebind swap a public answer for 169.254.169.254 between
    the check and the connect."""
    if not hostname:
        raise ValueError("empty host")
    try:
        infos = socket.getaddrinfo(hostname, None, type=socket.SOCK_STREAM)
    except OSError:
        raise ValueError(f"cannot resolve {hostname!r}")
    if not infos:
        raise ValueError(f"cannot resolve {hostname!r}")
    addrs = []
    for info in infos:
        ip = ipaddress.ip_address(info[4][0])
        if not ip.is_global and not ALLOW_PRIVATE:
            raise ValueError(f"non-public address for {hostname!r}")
        addrs.append(str(ip))
    # prefer IPv4: the demo host may lack a v6 route
    v4 = [a for a in addrs if ":" not in a]
    return (v4 or addrs)[0]


class _PinnedHTTPSConnection(http.client.HTTPSConnection):
    """HTTPSConnection that dials a pre-validated IP while doing SNI and
    certificate verification against the original hostname."""

    def __init__(self, ip, port, server_hostname, **kw):
        super().__init__(ip, port, **kw)
        self._server_hostname = server_hostname

    def connect(self):
        sock = socket.create_connection((self.host, self.port),
                                        self.timeout)
        self.sock = self._context.wrap_socket(
            sock, server_hostname=self._server_hostname)


def fetch_image_url(target: str, timeout: float = 10,
                    max_redirects: int = 5) -> bytes:
    """http(s)-only, public-address-only, size-capped fetch of a
    user-supplied image URL. Each hop (including every redirect) is
    resolved once and dialed by the validated IP with the Host header /
    TLS SNI pinned to the URL's hostname, so DNS rebinding between
    check and connect cannot redirect the fetch."""
    for _ in range(max_redirects + 1):
        parsed = urllib.parse.urlparse(target)
        if parsed.scheme not in ("http", "https"):
            raise ValueError("non-http(s) URL")
        host = parsed.hostname
        ip = _resolve_pinned(host)
        port = parsed.port or (443 if parsed.scheme == "https" else 80)
        if parsed.scheme == "https":
            conn = _PinnedHTTPSConnection(
                ip, port, server_hostname=host, timeout=timeout,
                context=ssl.create_default_context())
        else:
            conn = http.client.HTTPConnection(ip, port, timeout=timeout)
        try:
            path = parsed.path or "/"
            if parsed.query:
                path += "?" + parsed.query
            host_hdr = f"[{host}]" if ":" in host else host
            default = 443 if parsed.scheme == "https" else 80
            hdr_host = host_hdr if port == default else \
                f"{host_hdr}:{port}"
            conn.request("GET", path, headers={"Host": hdr_host,
                                               "User-Agent": "webdemo"})
            resp = conn.getresponse()
            if resp.status in (301, 302, 303, 307, 308):
                loc = resp.getheader("Location")
                if not loc:
                    raise ValueError("redirect without Location")
                target = urllib.parse.urljoin(target, loc)
                continue
            if resp.status != 200:
                raise ValueError(f"HTTP {resp.status}")
            data = resp.read(MAX_FETCH_BYTES + 1)
            if len(data) > MAX_FETCH_BYTES:
                raise ValueError("response too large")
            return data
        finally:
            conn.close()
    raise ValueError("too many redirects")

PAGE = """<!doctype html>
<html><head><title>rram-caffe-simulation-tpu demo</title></head>
<body style="font-family: sans-serif; max-width: 40em; margin: 2em auto">
<h1>Classification demo</h1>
<p>TPU-native framework serving <code>{model}</code>.</p>
{banner}
<form action="/classify_url" method="get">
  <input type="text" name="imageurl" size="40"
         placeholder="http://... image URL">
  <input type="submit" value="Classify URL">
</form>
<form action="/classify_upload" method="post"
      enctype="multipart/form-data">
  <input type="file" name="imagefile">
  <input type="submit" value="Classify Upload">
</form>
{result}
</body></html>
"""


def render_result(image_b64, preds, seconds):
    rows = "\n".join(
        f"<tr><td>{html.escape(name)}</td><td>{prob:.5f}</td>"
        f"<td><meter value='{prob:.5f}'></meter></td></tr>"
        for name, prob in preds)
    return (f"<h2>Top predictions ({seconds:.3f} s)</h2>"
            f"<table border='1' cellpadding='4'>"
            f"<tr><th>label</th><th>probability</th><th></th></tr>"
            f"{rows}</table>"
            f"<p><img src='data:image/png;base64,{image_b64}' "
            f"style='max-width: 16em'></p>")


class DemoClassifier:
    """api.Classifier plus a label list; returns top-5 (label, prob)."""

    def __init__(self, model_def, pretrained_model, labels_file=None,
                 mean_file=None, image_dim=256, raw_scale=255.0,
                 channel_swap=(2, 1, 0)):
        from rram_caffe_simulation_tpu.api import Classifier
        mean = None
        if mean_file:
            mean = np.load(mean_file).mean(1).mean(1)
        self.model_def = model_def
        self.net = Classifier(model_def, pretrained_model,
                              image_dims=(image_dim, image_dim),
                              raw_scale=raw_scale, mean=mean,
                              channel_swap=channel_swap)
        n_classes = None
        self.labels = None
        if labels_file:
            with open(labels_file) as f:
                # synset files are "id name, synonym..."; plain files are
                # one label per line — take everything after the first
                # token if it looks like a synset id, else the whole line
                lines = [l.strip() for l in f if l.strip()]
            self.labels = [
                " ".join(l.split(" ")[1:]).split(",")[0]
                if l.split(" ")[0].startswith("n") and
                l.split(" ")[0][1:].isdigit() else l
                for l in lines]

    def classify(self, image):
        """image: HxWxC float array in [0,1]. -> (ok, payload, seconds)"""
        try:
            t0 = time.time()
            scores = self.net.predict([image], oversample=True).flatten()
            dt = time.time() - t0
            top = (-scores).argsort()[:5]
            names = (self.labels if self.labels is not None
                     else [f"class {i}" for i in range(len(scores))])
            preds = [(names[i] if i < len(names) else f"class {i}",
                      float(scores[i])) for i in top]
            return True, preds, dt
        except Exception as err:  # surface as a banner, not a 500
            return False, (f"Something went wrong when classifying the "
                           f"image ({err}). Maybe try another one?"), 0.0


def decode_image(data: bytes):
    """bytes -> (HxWxC float [0,1] array, png base64 for re-display)."""
    from PIL import Image
    im = Image.open(io.BytesIO(data)).convert("RGB")
    buf = io.BytesIO()
    scale = 256.0 / max(im.width, im.height, 256)
    im.resize((max(1, int(im.width * scale)),
               max(1, int(im.height * scale)))).save(buf, "PNG")
    arr = np.asarray(im, dtype=np.float32) / 255.0
    return arr, base64.b64encode(buf.getvalue()).decode("ascii")


def parse_multipart(body: bytes, content_type: str):
    """Extract (filename, payload) of the first file field in a
    multipart/form-data body."""
    for token in content_type.split(";"):
        token = token.strip()
        if token.startswith("boundary="):
            boundary = token[len("boundary="):].strip('"').encode()
            break
    else:
        raise ValueError("multipart body without boundary")
    # parts are delimited by \r\n--boundary; the payload's own bytes may
    # legitimately end in CR/LF/'-', so strip exactly the one trailing
    # \r\n that belongs to the delimiter
    for part in body.split(b"--" + boundary):
        if b"\r\n\r\n" not in part:
            continue
        head, _, payload = part.partition(b"\r\n\r\n")
        if b"filename=" in head:
            if payload.endswith(b"\r\n"):
                payload = payload[:-2]
            name = ""
            for line in head.split(b"\r\n"):
                if not line.lower().startswith(b"content-disposition"):
                    continue
                for piece in line.split(b";"):
                    piece = piece.strip()
                    if piece.startswith(b"filename="):
                        name = piece[len(b"filename="):].strip(b'"') \
                            .decode("utf-8", "replace")
            return name, payload
    raise ValueError("no file field in upload")


def make_server(clf: DemoClassifier, port: int = 5000,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:

    class Handler(BaseHTTPRequestHandler):
        def _page(self, banner="", result="", status=200):
            doc = PAGE.format(model=html.escape(clf.model_def),
                              banner=banner, result=result).encode()
            self.send_response(status)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(doc)))
            self.end_headers()
            self.wfile.write(doc)

        def _classify(self, data: bytes):
            try:
                image, b64 = decode_image(data)
            except Exception:
                return self._page(banner="<p><b>Cannot open image.</b></p>")
            ok, payload, dt = clf.classify(image)
            if not ok:
                return self._page(
                    banner=f"<p><b>{html.escape(payload)}</b></p>")
            self._page(result=render_result(b64, payload, dt))

        def do_GET(self):
            url = urllib.parse.urlparse(self.path)
            if url.path == "/":
                return self._page()
            if url.path == "/classify_url":
                q = urllib.parse.parse_qs(url.query)
                target = (q.get("imageurl") or [""])[0]
                try:
                    data = fetch_image_url(target)
                except Exception:
                    return self._page(
                        banner="<p><b>Cannot open that URL.</b></p>")
                return self._classify(data)
            self.send_error(404)

        def do_POST(self):
            if self.path != "/classify_upload":
                return self.send_error(404)
            length = int(self.headers.get("Content-Length", "0"))
            ctype = self.headers.get("Content-Type", "")
            body = self.rfile.read(length)
            try:
                name, data = parse_multipart(body, ctype)
            except ValueError as err:
                return self._page(
                    banner=f"<p><b>{html.escape(str(err))}</b></p>")
            ext = name.rsplit(".", 1)[-1].lower() if "." in name else ""
            if ext not in ALLOWED_EXT:
                return self._page(banner=(
                    "<p><b>Only image uploads are allowed "
                    f"({', '.join(sorted(ALLOWED_EXT))}).</b></p>"))
            self._classify(data)

        def log_message(self, fmt, *args):  # quiet by default
            if os.environ.get("WEB_DEMO_LOG"):
                super().log_message(fmt, *args)

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--model-def", required=True)
    p.add_argument("--pretrained-model", required=True)
    p.add_argument("--labels", default="",
                   help="label file: one per line, or synset format")
    p.add_argument("--mean-file", default="", help=".npy pixel mean")
    p.add_argument("--image-dim", type=int, default=256)
    p.add_argument("--raw-scale", type=float, default=255.0)
    p.add_argument("--port", type=int, default=5000)
    p.add_argument("--allow-private-urls", action="store_true",
                   help="permit classify_url fetches from loopback/"
                        "private addresses (local development only)")
    args = p.parse_args(argv)
    if args.allow_private_urls:
        global ALLOW_PRIVATE
        ALLOW_PRIVATE = True
    clf = DemoClassifier(args.model_def, args.pretrained_model,
                         labels_file=args.labels or None,
                         mean_file=args.mean_file or None,
                         image_dim=args.image_dim,
                         raw_scale=args.raw_scale)
    srv = make_server(clf, port=args.port)
    print(f"Serving on http://{srv.server_address[0]}:"
          f"{srv.server_address[1]}/")
    srv.serve_forever()


if __name__ == "__main__":
    main()

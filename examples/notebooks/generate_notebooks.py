"""Generate the tutorial notebooks (.ipynb) from their cell sources.

The reference ships 8 Jupyter notebooks in `examples/` (00-classification,
01-learning-lenet, net_surgery, brewing-logreg, ...). This framework's
tutorial content lives primarily in runnable scripts (CI-testable), and
this generator renders the notebook COUNTERPARTS for users who want the
interactive form — same public API, same flows as the scripts they
mirror. Regenerate with:

    python examples/notebooks/generate_notebooks.py
"""
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))


def nb(cells):
    return {
        "cells": cells,
        "metadata": {
            "kernelspec": {"display_name": "Python 3",
                           "language": "python", "name": "python3"},
            "language_info": {"name": "python", "version": "3.12"},
        },
        "nbformat": 4,
        "nbformat_minor": 5,
    }


def md(text):
    return {"cell_type": "markdown", "metadata": {},
            "source": text.splitlines(keepends=True)}


def code(text):
    return {"cell_type": "code", "execution_count": None,
            "metadata": {}, "outputs": [],
            "source": text.strip("\n").splitlines(keepends=True)}


LEARNING_LENET = nb([
    md("""# Learning LeNet

Counterpart of the reference's `01-learning-lenet.ipynb`: define the
solver in Python, run training steps, and inspect blobs/weights as the
net learns — through the pycaffe-style `api` facade. Run from the repo
root."""),
    code("""
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
from rram_caffe_simulation_tpu import api as caffe
"""),
    code("""
# a small LeNet on the bundled handwritten-digits corpus via net_spec
from rram_caffe_simulation_tpu.api import layers as L, params as P, NetSpec
from sklearn.datasets import load_digits

digits = load_digits()
X = digits.images.astype(np.float32)[:, None] / 16.0   # (N,1,8,8)
y = digits.target.astype(np.float32)
"""),
    code("""
n = NetSpec()
n.data, n.label = L.Input(ntop=2,
    input_param=dict(shape=[dict(dim=[64, 1, 8, 8]), dict(dim=[64])]))
n.conv1 = L.Convolution(n.data, kernel_size=3, num_output=20,
                        weight_filler=dict(type='xavier'))
n.pool1 = L.Pooling(n.conv1, kernel_size=2, stride=2,
                    pool=P.Pooling.MAX)
n.ip1 = L.InnerProduct(n.pool1, num_output=64,
                       weight_filler=dict(type='xavier'))
n.relu1 = L.ReLU(n.ip1, in_place=True)
n.ip2 = L.InnerProduct(n.relu1, num_output=10,
                       weight_filler=dict(type='xavier'))
n.loss = L.SoftmaxWithLoss(n.ip2, n.label)
import tempfile
workdir = tempfile.mkdtemp(prefix='lenet_nb_')
proto_path = os.path.join(workdir, 'lenet_auto.prototxt')
open(proto_path, 'w').write(str(n.to_proto()))
"""),
    code("""
from rram_caffe_simulation_tpu.proto import pb
from rram_caffe_simulation_tpu.solver import Solver

sp = pb.SolverParameter()
sp.net = proto_path
sp.base_lr = 0.1; sp.momentum = 0.9; sp.lr_policy = 'fixed'
sp.max_iter = 200; sp.display = 50; sp.random_seed = 0
sp.snapshot_prefix = os.path.join(workdir, 'lenet_auto')

rng = np.random.RandomState(0)
def feed():
    idx = rng.randint(0, len(X) - 200, 64)   # hold out the tail
    return {'data': X[idx], 'label': y[idx]}
solver = Solver(sp, train_feed=feed)
solver.step(200)
"""),
    code("""
# inspect learned conv1 filters and score the held-out tail
w = np.asarray(solver.params['conv1'][0])
print('conv1 filters', w.shape, 'spread', w.std())
blobs, _ = solver.net.apply(solver.params,
                            {'data': X[-200:-136], 'label': y[-200:-136]})
pred = np.asarray(blobs['ip2']).argmax(1)
print('held-out accuracy:', (pred == y[-200:-136]).mean())
"""),
])


NET_SURGERY = nb([
    md("""# Net surgery

Counterpart of `net_surgery.ipynb`: cast an InnerProduct classifier to
its fully-convolutional twin by reshaping the SAME parameters, then get
dense sliding-window outputs. Mirrors
`examples/net_surgery/net_surgery.py` (the CI-tested script)."""),
    code("""
import os, sys
sys.path.insert(0, os.getcwd())
import importlib.util
spec = importlib.util.spec_from_file_location(
    'net_surgery_mod', 'examples/net_surgery/net_surgery.py')
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.main()   # prints the designer-filter + fc->conv parity numbers
"""),
])


BREWING_LOGREG = nb([
    md("""# Brewing logistic regression, then going deeper

Counterpart of `brewing-logreg.ipynb`: logistic regression as a
one-layer net via HDF5Data, then a nonlinear net on the same data beats
it — the reference notebook's central claim, reproduced by
`examples/hdf5_classification/run_hdf5_classification.py`."""),
    code("""
import os, sys
sys.path.insert(0, os.getcwd())
import importlib.util, tempfile
spec = importlib.util.spec_from_file_location(
    'run_hdf5', 'examples/hdf5_classification/run_hdf5_classification.py')
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
X, y = mod.make_dataset(n=4000)
d = tempfile.mkdtemp()
mod.write_hdf5(d, X, y, split=3000)
acc_lr = mod.solve('LogisticRegressionNet', 0, d, max_iter=300)
acc_nn = mod.solve('NonlinearNet', 40, d, max_iter=300)
print(f'logreg {acc_lr:.3f}  vs  two-layer ReLU {acc_nn:.3f}')
"""),
])


NOTEBOOKS = {
    "01-learning-lenet.ipynb": LEARNING_LENET,
    "net_surgery.ipynb": NET_SURGERY,
    "brewing-logreg.ipynb": BREWING_LOGREG,
}


def main():
    for name, book in NOTEBOOKS.items():
        path = os.path.join(HERE, name)
        with open(path, "w") as f:
            json.dump(book, f, indent=1)
        print("wrote", path)


if __name__ == "__main__":
    main()

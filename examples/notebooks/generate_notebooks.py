"""Generate the tutorial notebooks (.ipynb) from their cell sources.

The reference ships 8 Jupyter notebooks in `examples/` (00-classification,
01-learning-lenet, net_surgery, brewing-logreg, ...). This framework's
tutorial content lives primarily in runnable scripts (CI-testable), and
this generator renders the notebook COUNTERPARTS for users who want the
interactive form — same public API, same flows as the scripts they
mirror. Regenerate with:

    python examples/notebooks/generate_notebooks.py
"""
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))


def nb(cells):
    return {
        "cells": cells,
        "metadata": {
            "kernelspec": {"display_name": "Python 3",
                           "language": "python", "name": "python3"},
            "language_info": {"name": "python", "version": "3.12"},
        },
        "nbformat": 4,
        "nbformat_minor": 5,
    }


def md(text):
    return {"cell_type": "markdown", "metadata": {},
            "source": text.splitlines(keepends=True)}


def code(text):
    return {"cell_type": "code", "execution_count": None,
            "metadata": {}, "outputs": [],
            "source": text.strip("\n").splitlines(keepends=True)}


LEARNING_LENET = nb([
    md("""# Learning LeNet

Counterpart of the reference's `01-learning-lenet.ipynb`: define the
solver in Python, run training steps, and inspect blobs/weights as the
net learns — through the pycaffe-style `api` facade. Run from the repo
root."""),
    code("""
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
from rram_caffe_simulation_tpu import api as caffe
"""),
    code("""
# a small LeNet on the bundled handwritten-digits corpus via net_spec
from rram_caffe_simulation_tpu.api import layers as L, params as P, NetSpec
from sklearn.datasets import load_digits

digits = load_digits()
X = digits.images.astype(np.float32)[:, None] / 16.0   # (N,1,8,8)
y = digits.target.astype(np.float32)
"""),
    code("""
n = NetSpec()
n.data, n.label = L.Input(ntop=2,
    input_param=dict(shape=[dict(dim=[64, 1, 8, 8]), dict(dim=[64])]))
n.conv1 = L.Convolution(n.data, kernel_size=3, num_output=20,
                        weight_filler=dict(type='xavier'))
n.pool1 = L.Pooling(n.conv1, kernel_size=2, stride=2,
                    pool=P.Pooling.MAX)
n.ip1 = L.InnerProduct(n.pool1, num_output=64,
                       weight_filler=dict(type='xavier'))
n.relu1 = L.ReLU(n.ip1, in_place=True)
n.ip2 = L.InnerProduct(n.relu1, num_output=10,
                       weight_filler=dict(type='xavier'))
n.loss = L.SoftmaxWithLoss(n.ip2, n.label)
import tempfile
workdir = tempfile.mkdtemp(prefix='lenet_nb_')
proto_path = os.path.join(workdir, 'lenet_auto.prototxt')
open(proto_path, 'w').write(str(n.to_proto()))
"""),
    code("""
from rram_caffe_simulation_tpu.proto import pb
from rram_caffe_simulation_tpu.solver import Solver

sp = pb.SolverParameter()
sp.net = proto_path
sp.base_lr = 0.1; sp.momentum = 0.9; sp.lr_policy = 'fixed'
sp.max_iter = 200; sp.display = 50; sp.random_seed = 0
sp.snapshot_prefix = os.path.join(workdir, 'lenet_auto')

rng = np.random.RandomState(0)
def feed():
    idx = rng.randint(0, len(X) - 200, 64)   # hold out the tail
    return {'data': X[idx], 'label': y[idx]}
solver = Solver(sp, train_feed=feed)
solver.step(200)
"""),
    code("""
# inspect learned conv1 filters and score the held-out tail
w = np.asarray(solver.params['conv1'][0])
print('conv1 filters', w.shape, 'spread', w.std())
blobs, _ = solver.net.apply(solver.params,
                            {'data': X[-200:-136], 'label': y[-200:-136]})
pred = np.asarray(blobs['ip2']).argmax(1)
print('held-out accuracy:', (pred == y[-200:-136]).mean())
"""),
])


NET_SURGERY = nb([
    md("""# Net surgery

Counterpart of `net_surgery.ipynb`: cast an InnerProduct classifier to
its fully-convolutional twin by reshaping the SAME parameters, then get
dense sliding-window outputs. Mirrors
`examples/net_surgery/net_surgery.py` (the CI-tested script)."""),
    code("""
import os, sys
sys.path.insert(0, os.getcwd())
import importlib.util
spec = importlib.util.spec_from_file_location(
    'net_surgery_mod', 'examples/net_surgery/net_surgery.py')
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.main()   # prints the designer-filter + fc->conv parity numbers
"""),
])


BREWING_LOGREG = nb([
    md("""# Brewing logistic regression, then going deeper

Counterpart of `brewing-logreg.ipynb`: logistic regression as a
one-layer net via HDF5Data, then a nonlinear net on the same data beats
it — the reference notebook's central claim, reproduced by
`examples/hdf5_classification/run_hdf5_classification.py`."""),
    code("""
import os, sys
sys.path.insert(0, os.getcwd())
import importlib.util, tempfile
spec = importlib.util.spec_from_file_location(
    'run_hdf5', 'examples/hdf5_classification/run_hdf5_classification.py')
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
X, y = mod.make_dataset(n=4000)
d = tempfile.mkdtemp()
mod.write_hdf5(d, X, y, split=3000)
acc_lr = mod.solve('LogisticRegressionNet', 0, d, max_iter=300)
acc_nn = mod.solve('NonlinearNet', 40, d, max_iter=300)
print(f'logreg {acc_lr:.3f}  vs  two-layer ReLU {acc_nn:.3f}')
"""),
])


CLASSIFICATION = nb([
    md("""# Classifying images with a trained net

Counterpart of the reference's `00-classification.ipynb`: load a net +
weights into the `Classifier` facade, classify an image, read the top
predictions, and look inside the net at intermediate blobs. The
reference downloads CaffeNet weights; this image has no network, so we
first brew a small classifier on generated images (same API end to
end)."""),
    code("""
import os, sys, tempfile
sys.path.insert(0, os.getcwd())
import numpy as np
from rram_caffe_simulation_tpu import api as caffe
from rram_caffe_simulation_tpu.proto import pb
from rram_caffe_simulation_tpu.utils import io as uio
from google.protobuf import text_format

workdir = tempfile.mkdtemp(prefix='cls_nb_')
"""),
    code("""
# three synthetic classes distinguished by channel dominance
rng = np.random.RandomState(0)
def make_image(cls, n=1):
    img = rng.rand(n, 3, 24, 24).astype(np.float32) * 0.3
    img[:, cls] += 0.7
    return img
LABELS = ['reddish', 'greenish', 'blueish']

TRAIN_NET = '''
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 32 dim: 3 dim: 24 dim: 24 } } }
layer { name: "lab" type: "Input" top: "label"
  input_param { shape { dim: 32 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 2
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "fc" type: "InnerProduct" bottom: "conv1" top: "fc"
  inner_product_param { num_output: 3
    weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc"
  bottom: "label" }
'''
sp = pb.SolverParameter()
text_format.Parse(TRAIN_NET, sp.net_param)
sp.base_lr = 0.05; sp.momentum = 0.9; sp.lr_policy = 'fixed'
sp.max_iter = 60; sp.display = 0; sp.random_seed = 1
sp.snapshot_prefix = os.path.join(workdir, 'cls')

from rram_caffe_simulation_tpu.solver import Solver
def feed():
    y = rng.randint(0, 3, 32)
    return {'data': np.concatenate([make_image(c) for c in y]),
            'label': y.astype(np.float32)}
solver = Solver(sp, train_feed=feed)
solver.step(60)
weights = os.path.join(workdir, 'cls.caffemodel')
uio.write_proto_binary(weights,
                       solver.net.to_proto(solver.params))
"""),
    code("""
# deploy net (Input only) + Classifier facade, reference flow
DEPLOY = '''
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 1 dim: 3 dim: 24 dim: 24 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 2 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "fc" type: "InnerProduct" bottom: "conv1" top: "fc"
  inner_product_param { num_output: 3 } }
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
'''
deploy = os.path.join(workdir, 'deploy.prototxt')
open(deploy, 'w').write(DEPLOY)
clf = caffe.Classifier(deploy, weights, image_dims=(24, 24),
                       raw_scale=1.0)
img = make_image(2)[0].transpose(1, 2, 0)  # HWC like caffe.io images
probs = clf.predict([img], oversample=False)[0]
for i in np.argsort(-probs):
    print(f'{LABELS[i]:<9} {probs[i]:.4f}')
assert probs.argmax() == 2
"""),
    code("""
# look inside the net: blob shapes + conv1 activations, pycaffe-style
net = caffe.Net(deploy, weights, pb.TEST)
net.blobs['data'].data[...] = make_image(0)
net.forward()
for name, blob in net.blobs.items():
    print(f'{name:<6} {blob.data.shape}')
acts = net.blobs['conv1'].data
print('conv1 activation stats: mean %.3f  max %.3f'
      % (acts.mean(), acts.max()))
"""),
])


FINE_TUNING = nb([
    md("""# Fine-tuning a pretrained net

Counterpart of `02-fine-tuning.ipynb` (CaffeNet -> Flickr style): start
from weights trained on one task and fine-tune on another, against a
from-scratch baseline at the same iteration budget — the pretrained
start learns faster. Tasks: digits 0-4 (pretrain) -> digits 5-9
(fine-tune), on scikit-learn's bundled handwritten digits."""),
    code("""
import os, sys, tempfile
sys.path.insert(0, os.getcwd())
import numpy as np
from sklearn.datasets import load_digits
from rram_caffe_simulation_tpu.proto import pb
from rram_caffe_simulation_tpu.solver import Solver
from rram_caffe_simulation_tpu.utils import io as uio
from google.protobuf import text_format

digits = load_digits()
X = digits.images.astype(np.float32)[:, None] / 16.0
y = digits.target
lo = y < 5            # pretraining task
hi = ~lo              # fine-tuning task (labels shifted to 0..4)
workdir = tempfile.mkdtemp(prefix='ft_nb_')

NET = '''
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 64 dim: 1 dim: 8 dim: 8 } } }
layer { name: "lab" type: "Input" top: "label"
  input_param { shape { dim: 64 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 16 kernel_size: 3
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "ip1" type: "InnerProduct" bottom: "conv1" top: "ip1"
  inner_product_param { num_output: 48
    weight_filler { type: "xavier" } } }
layer { name: "relu2" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2"
  bottom: "label" }
'''

def make_solver(Xs, ys, seed, weights=None, lr=0.05, iters=80):
    sp = pb.SolverParameter()
    text_format.Parse(NET, sp.net_param)
    sp.base_lr = lr; sp.momentum = 0.9; sp.lr_policy = 'fixed'
    sp.max_iter = iters; sp.display = 0; sp.random_seed = seed
    sp.snapshot_prefix = os.path.join(workdir, f's{seed}')
    rng = np.random.RandomState(seed)
    def feed():
        idx = rng.randint(0, len(Xs), 64)
        return {'data': Xs[idx], 'label': ys[idx].astype(np.float32)}
    s = Solver(sp, train_feed=feed)
    if weights:
        # name-matched weight loading, the CLI --weights flow
        s.params = s.net.copy_trained_from(s.params, weights)
    return s

def accuracy(s, Xs, ys):
    correct = 0
    for i in range(0, 256, 64):
        blobs, _ = s.net.apply(
            s.params, {'data': Xs[i:i+64],
                       'label': ys[i:i+64].astype(np.float32)})
        correct += (np.asarray(blobs['ip2']).argmax(1)
                    == ys[i:i+64]).sum()
    return correct / 256
"""),
    code("""
# 1) pretrain on digits 0-4 and snapshot the weights
pre = make_solver(X[lo], y[lo], seed=0, iters=150)
pre.step(150)
pretrained = os.path.join(workdir, 'pretrained.caffemodel')
uio.write_proto_binary(pretrained, pre.net.to_proto(pre.params))
print('pretrain accuracy (0-4):', accuracy(pre, X[lo], y[lo]))
"""),
    code("""
# 2) fine-tune on 5-9 from those weights vs train from scratch,
#    SAME small iteration budget
SHORT = 40
ft = make_solver(X[hi], y[hi] - 5, seed=1, weights=pretrained,
                 iters=SHORT)
scratch = make_solver(X[hi], y[hi] - 5, seed=1, iters=SHORT)
ft.step(SHORT); scratch.step(SHORT)
acc_ft = accuracy(ft, X[hi], y[hi] - 5)
acc_scratch = accuracy(scratch, X[hi], y[hi] - 5)
print(f'fine-tuned {acc_ft:.3f}  vs  scratch {acc_scratch:.3f} '
      f'after {SHORT} iters')
assert acc_ft > acc_scratch  # the transferred conv features pay off
"""),
])


DETECTION = nb([
    md("""# R-CNN detection

Counterpart of `detection.ipynb`: run a classifier over region
proposals with the `Detector` facade (`api.detector`, the pycaffe
`detect_windows` flow) and keep the best-scoring windows. The reference
uses selective-search proposals over a downloaded image; here the
proposals are a sliding grid over a generated scene with a bright
'object' planted in one quadrant."""),
    code("""
import os, sys, tempfile
sys.path.insert(0, os.getcwd())
import numpy as np
from rram_caffe_simulation_tpu import api as caffe
from rram_caffe_simulation_tpu.proto import pb
from rram_caffe_simulation_tpu.utils import io as uio
from rram_caffe_simulation_tpu.solver import Solver
from google.protobuf import text_format

workdir = tempfile.mkdtemp(prefix='det_nb_')
rng = np.random.RandomState(0)

def scene_with_object(cx, cy):
    img = rng.rand(48, 48, 3).astype(np.float32) * 0.2
    img[cy - 6:cy + 6, cx - 6:cx + 6, 0] = 1.0   # bright red square
    return img
"""),
    code("""
# brew the window classifier: object-vs-background crops (16x16)
TRAIN_NET = '''
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 32 dim: 3 dim: 16 dim: 16 } } }
layer { name: "lab" type: "Input" top: "label"
  input_param { shape { dim: 32 } } }
layer { name: "fc1" type: "InnerProduct" bottom: "data" top: "fc1"
  inner_product_param { num_output: 16
    weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "fc1" top: "fc1" }
layer { name: "fc2" type: "InnerProduct" bottom: "fc1" top: "fc2"
  inner_product_param { num_output: 2
    weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc2"
  bottom: "label" }
'''
sp = pb.SolverParameter()
text_format.Parse(TRAIN_NET, sp.net_param)
sp.base_lr = 0.05; sp.momentum = 0.9; sp.lr_policy = 'fixed'
sp.max_iter = 80; sp.display = 0; sp.random_seed = 2
sp.snapshot_prefix = os.path.join(workdir, 'det')

def crop_batch():
    xs, ys = [], []
    for _ in range(32):
        obj = rng.rand() < 0.5
        patch = rng.rand(16, 16, 3).astype(np.float32) * 0.2
        if obj:
            patch[4:12, 4:12, 0] = 1.0
        xs.append(patch.transpose(2, 0, 1))
        ys.append(float(obj))
    return {'data': np.stack(xs), 'label': np.asarray(ys, np.float32)}
solver = Solver(sp, train_feed=crop_batch)
solver.step(80)
weights = os.path.join(workdir, 'det.caffemodel')
uio.write_proto_binary(weights, solver.net.to_proto(solver.params))
"""),
    code("""
DEPLOY = '''
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 1 dim: 3 dim: 16 dim: 16 } } }
layer { name: "fc1" type: "InnerProduct" bottom: "data" top: "fc1"
  inner_product_param { num_output: 16 } }
layer { name: "relu" type: "ReLU" bottom: "fc1" top: "fc1" }
layer { name: "fc2" type: "InnerProduct" bottom: "fc1" top: "fc2"
  inner_product_param { num_output: 2 } }
layer { name: "prob" type: "Softmax" bottom: "fc2" top: "prob" }
'''
deploy = os.path.join(workdir, 'deploy.prototxt')
open(deploy, 'w').write(DEPLOY)

det = caffe.Detector(deploy, weights)
image = scene_with_object(cx=36, cy=12)   # object in the NE quadrant
# detect_windows loads images by filename, like the reference flow
from PIL import Image
scene_png = os.path.join(workdir, 'scene.png')
Image.fromarray((np.clip(image, 0, 1) * 255).astype(np.uint8)) \
    .save(scene_png)
# sliding 16x16 proposals, stride 8 — (ymin, xmin, ymax, xmax)
windows = [(yy, xx, yy + 16, xx + 16)
           for yy in range(0, 33, 8) for xx in range(0, 33, 8)]
dets = det.detect_windows([(scene_png, np.asarray(windows))])
scores = np.asarray([d['prediction'][1] for d in dets])
best = windows[int(scores.argmax())]
print('best window (object score %.3f):' % scores.max(), best)
# the winning window must overlap the planted object at (36, 12)
assert best[1] <= 36 <= best[3] and best[0] <= 12 <= best[2]
print('top-3 windows:',
      [windows[i] for i in np.argsort(-scores)[:3]])
"""),
])


PASCAL_MULTILABEL = nb([
    md("""# Multilabel classification

Counterpart of `pascal-multilabel-with-datalayer.ipynb`: multilabel
targets (several classes can be present at once) trained with
`SigmoidCrossEntropyLoss`, plus a `Python` layer computing the batch
hamming accuracy inside the net — the two mechanisms the reference
notebook demonstrates on PASCAL. Data: synthetic 3-channel images where
each channel's presence is one label."""),
    code("""
import os, sys, tempfile
sys.path.insert(0, os.getcwd())
import numpy as np
from rram_caffe_simulation_tpu.proto import pb
from rram_caffe_simulation_tpu.solver import Solver
from google.protobuf import text_format

workdir = tempfile.mkdtemp(prefix='ml_nb_')
rng = np.random.RandomState(0)
N_CLASSES = 3

def multilabel_batch(n=32):
    labels = (rng.rand(n, N_CLASSES) < 0.4).astype(np.float32)
    imgs = rng.rand(n, 3, 12, 12).astype(np.float32) * 0.2
    for c in range(N_CLASSES):
        imgs[:, c] += labels[:, c, None, None] * 0.8
    return {'data': imgs, 'label': labels}
"""),
    code("""
# the hamming-accuracy Python layer (pascal_multilabel_datalayers.py
# counterpart): user code with Caffe's setup/reshape/forward contract
layer_mod = os.path.join(workdir, 'hamming_layer.py')
open(layer_mod, 'w').write('''
import numpy as np

class HammingAccuracyLayer:
    # top[0] = mean(1 - |round(sigmoid(score)) - label|)
    def setup(self, bottom, top):
        pass
    def reshape(self, bottom, top):
        top[0].reshape(1)
    def forward(self, bottom, top):
        pred = 1.0 / (1.0 + np.exp(-bottom[0].data)) > 0.5
        top[0].data[...] = 1.0 - np.abs(
            pred.astype(np.float32) - bottom[1].data).mean()
''')
sys.path.insert(0, workdir)

NET = '''
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 32 dim: 3 dim: 12 dim: 12 } } }
layer { name: "lab" type: "Input" top: "label"
  input_param { shape { dim: 32 dim: 3 } } }
layer { name: "fc1" type: "InnerProduct" bottom: "data" top: "fc1"
  inner_product_param { num_output: 24
    weight_filler { type: "xavier" } } }
layer { name: "relu" type: "ReLU" bottom: "fc1" top: "fc1" }
layer { name: "score" type: "InnerProduct" bottom: "fc1" top: "score"
  inner_product_param { num_output: 3
    weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SigmoidCrossEntropyLoss" bottom: "score"
  bottom: "label" top: "loss" }
layer { name: "hamming" type: "Python" bottom: "score" bottom: "label"
  top: "hamming"
  python_param { module: "hamming_layer"
                 layer: "HammingAccuracyLayer" } }
'''
sp = pb.SolverParameter()
text_format.Parse(NET, sp.net_param)
sp.base_lr = 0.05; sp.momentum = 0.9; sp.lr_policy = 'fixed'
sp.max_iter = 120; sp.display = 0; sp.random_seed = 3
sp.snapshot_prefix = os.path.join(workdir, 'ml')
solver = Solver(sp, train_feed=multilabel_batch)
"""),
    code("""
# hamming accuracy before vs after training
def hamming_now():
    batch = multilabel_batch()
    blobs, _ = solver.net.apply(solver.params, batch)
    return float(np.asarray(blobs['hamming']).ravel()[0])

before = hamming_now()
solver.step(120)
after = hamming_now()
print(f'hamming accuracy: {before:.3f} -> {after:.3f}')
assert after > 0.9 and after > before
"""),
])


MNIST_SIAMESE = nb([
    md("""# Siamese network embedding

Counterpart of `siamese/mnist_siamese.ipynb`: train the shared-weight
siamese pair with `ContrastiveLoss` and check that the learned 2-D
embedding separates same-digit pairs from different-digit pairs —
through the CI-tested `examples/siamese/run_siamese.py` flow (dataset
pairing, weight sharing across the two towers, the margin loss)."""),
    code("""
import os, sys, subprocess
sys.path.insert(0, os.getcwd())
import importlib.util
spec = importlib.util.spec_from_file_location(
    'run_siamese_mod', 'examples/siamese/run_siamese.py')
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.ensure_datasets()                       # pair LMDBs from digits
subprocess.run([sys.executable,
                'examples/siamese/generate.py'], check=True)
"""),
    code("""
# train briefly and measure the embedding separation
# (mean distance of different-digit pairs vs same-digit pairs)
from rram_caffe_simulation_tpu.solver import Solver
from rram_caffe_simulation_tpu.utils.io import read_solver_param
param = read_solver_param('examples/siamese/mnist_siamese_solver.prototxt')
param.max_iter = 150
param.display = 0
param.ClearField('snapshot')
import tempfile
param.snapshot_prefix = os.path.join(
    tempfile.mkdtemp(prefix='siam_nb_'), 'siam')
solver = Solver(param)
solver.step(150)
same, diff = mod.embedding_separation(solver)
print(f'same-class {same:.3f}  different-class {diff:.3f}  '
      f'ratio {diff / max(same, 1e-9):.2f}x')
assert diff > same   # the margin loss pushes unlike pairs apart
"""),
])


NOTEBOOKS = {
    "00-classification.ipynb": CLASSIFICATION,
    "01-learning-lenet.ipynb": LEARNING_LENET,
    "02-fine-tuning.ipynb": FINE_TUNING,
    "net_surgery.ipynb": NET_SURGERY,
    "brewing-logreg.ipynb": BREWING_LOGREG,
    "detection.ipynb": DETECTION,
    "pascal-multilabel-with-datalayer.ipynb": PASCAL_MULTILABEL,
    "mnist_siamese.ipynb": MNIST_SIAMESE,
}


def main():
    for name, book in NOTEBOOKS.items():
        path = os.path.join(HERE, name)
        with open(path, "w") as f:
            json.dump(book, f, indent=1)
        print("wrote", path)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Feature-extraction example (reference examples/feature_extraction):
train (or load) CIFAR-10-quick, then dump pool3 + ip1 features of the
test LMDB to float-Datum LMDBs via the extract_features CLI subcommand,
and verify the round-trip.

    python examples/feature_extraction/run_feature_extraction.py \
        [--weights snapshot.caffemodel.h5] [--iters 200] [--batches 5]
"""
import argparse
import os
import shutil
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)

from rram_caffe_simulation_tpu.data.db import open_db  # noqa: E402
from rram_caffe_simulation_tpu.proto import pb  # noqa: E402
from rram_caffe_simulation_tpu.tools import caffe_cli  # noqa: E402
from rram_caffe_simulation_tpu.utils import io as uio  # noqa: E402


def train_quick(iters):
    """A short CIFAR-quick run on the sample LMDB to get weights."""
    from google.protobuf import text_format
    from rram_caffe_simulation_tpu.solver import Solver
    sp = pb.SolverParameter()
    with open(os.path.join(ROOT, "models", "cifar10_quick",
                           "cifar10_quick_lmdb_solver.prototxt")) as f:
        text_format.Merge(f.read(), sp)
    sp.max_iter = iters
    sp.display = max(iters // 4, 1)
    sp.ClearField("test_interval")
    sp.ClearField("test_iter")
    sp.snapshot = 0
    sp.snapshot_after_train = True
    sp.snapshot_prefix = os.path.join(HERE, "quick")
    solver = Solver(sp)
    solver.solve()
    return solver.snapshot_filename(".caffemodel.h5")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--weights", default="")
    p.add_argument("--iters", type=int, default=200)
    p.add_argument("--batches", type=int, default=5)
    args = p.parse_args(argv)
    os.chdir(ROOT)  # prototxt data sources are repo-root relative

    weights = args.weights or train_quick(args.iters)
    proto = os.path.join("models", "cifar10_quick",
                         "cifar10_quick_lmdb_train_test.prototxt")
    dbs = [os.path.join(HERE, "features_pool3_lmdb"),
           os.path.join(HERE, "features_ip1_lmdb")]
    for db in dbs:
        shutil.rmtree(db, ignore_errors=True)

    rc = caffe_cli.main([
        "extract_features", weights, proto, "pool3,ip1", ",".join(dbs),
        str(args.batches), "lmdb"])
    assert rc in (0, None), rc

    # round-trip check: N batches x batch_size float Datums per blob
    npar = uio.read_net_param(proto)
    batch = next(lp.data_param.batch_size for lp in npar.layer
                 if lp.type == "Data" and
                 any(r.phase == pb.TEST for r in lp.include))
    for db_path, blob in zip(dbs, ("pool3", "ip1")):
        db = open_db(db_path, "lmdb")
        cur = db.cursor()
        total = len(db)
        dims = None
        for n in range(total):  # the cursor wraps like DataReader's
            datum = pb.Datum.FromString(cur.value())
            assert cur.key().decode() == f"{n:010d}"
            vec = np.asarray(datum.float_data, np.float32)
            assert vec.size == datum.channels * datum.height * datum.width
            dims = (datum.channels, datum.height, datum.width)
            cur.next()
        n = total
        print(f"{blob}: {n} feature vectors of {dims} in {db_path}")
        assert n == args.batches * batch
    print("feature extraction OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Hardware co-design explorer: sweep (fault-process mix x sigma x
adc_bits x lifetime distribution x mitigation strategy) jointly and
report the Pareto front.

The 1000-config sweep machinery explores the (mean, std) lifetime grid
inside one jitted program; this driver adds the axes that change the
TRACED program — which fault physics runs (fault/processes/ registry),
the crossbar read-noise sigma, the ADC resolution (`quantize_ste`, the
NEON tradeoff), and the mitigation strategy — by bucketing the joint
grid with `fault.codesign.group_static`: one compiled SweepRunner per
static bucket, the (mean, std) entries riding its vectorized lanes.

Outputs (under --out):

- `results.jsonl` — one record per evaluated config: every axis value
  plus `loss` (final per-config loss), `broken` (final broken-cell
  fraction), `adc_cost_bits` (adc_bits, with 0 = full precision
  counted as 32 — the hardware-cost proxy a cheaper ADC improves), and
  `wall_seconds` for the bucket.
- `pareto_report.json` — the non-dominated front over
  (--metric-x, --metric-y), default (loss, adc_cost_bits): the
  accuracy-vs-ADC-cost curve, with the process mix and mitigation
  strategy as the free design variables along it.

    python examples/gaussian_failure/run_codesign.py \
        --processes endurance_stuck_at,read_disturb \
        --adc-bits 2,4 --sigmas 0.0 --iters 300 --out codesign0

Exit code 0 = report written with a non-degenerate front, 65 = the
front collapsed to a single point (axes exposed no tradeoff — widen
them), 2 = usage error.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)

DEGENERATE_EXIT = 65


def _floats(text):
    return [float(x) for x in str(text).split(",") if x.strip()]


def _ints(text):
    return [int(x) for x in str(text).split(",") if x.strip()]


def _strs(text):
    return [x.strip() for x in str(text).split(",") if x.strip()]


def main(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    p.add_argument("--solver", default=(
        "models/cifar10_quick/cifar10_quick_lmdb_solver.prototxt"),
        help="solver prototxt each bucket's Solver is built from "
             "(failure pattern / rram_forward / strategy / seed are "
             "overridden per bucket here)")
    p.add_argument("--processes", default="endurance_stuck_at",
                   help="comma-separated fault-process specs "
                        "(fault/processes/ syntax; ':' params and '+' "
                        "stacks allowed — commas inside a spec are "
                        "not, use one-param processes or defaults)")
    p.add_argument("--sigmas", default="0.0",
                   help="comma-separated crossbar read-noise sigmas")
    p.add_argument("--adc-bits", default="0,4",
                   help="comma-separated ADC resolutions (0 = full "
                        "precision; 1 is invalid — symmetric quantizer"
                        ")")
    p.add_argument("--strategies", default="none",
                   help="comma-separated mitigation strategies: none "
                        "or threshold:T (e.g. threshold:0.001)")
    p.add_argument("--tiles", default="1x1",
                   help="comma-separated tiled-crossbar-mapping specs "
                        "(fault/mapping.py TileSpec syntax: '1x1' = "
                        "untiled, 'GRxGC' grids, 'cells=RxC' physical "
                        "arrays) — the CIM-Explorer mapping axis, "
                        "swept jointly with the rest")
    p.add_argument("--means", default="400,800",
                   help="comma-separated lifetime means (the per-lane "
                        "Monte-Carlo axis)")
    p.add_argument("--stds", default="100",
                   help="comma-separated lifetime stds (crossed with "
                        "--means)")
    p.add_argument("--iters", type=int, default=300)
    p.add_argument("--chunk", type=int, default=25)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--metric-x", default="loss",
                   help="quality metric (minimized unless "
                        "--maximize-x)")
    p.add_argument("--metric-y", default="adc_cost_bits",
                   help="hardware-cost metric (minimized unless "
                        "--maximize-y)")
    p.add_argument("--maximize-x", action="store_true")
    p.add_argument("--maximize-y", action="store_true")
    p.add_argument("--out", required=True,
                   help="output directory (results.jsonl + "
                        "pareto_report.json)")
    args = p.parse_args(argv)

    os.chdir(REPO)
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)

    from rram_caffe_simulation_tpu.fault import codesign
    from rram_caffe_simulation_tpu.fault.mapping import TileSpec
    from rram_caffe_simulation_tpu.fault.processes import FaultSpec
    from rram_caffe_simulation_tpu.parallel import SweepRunner
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.utils.io import read_solver_param

    axes = {
        "process": [FaultSpec.parse(s).canonical()
                    for s in _strs(args.processes)],
        "sigma": _floats(args.sigmas),
        "adc_bits": _ints(args.adc_bits),
        "strategy": _strs(args.strategies),
        # canonicalized up front so the records/report carry the
        # canonical tile spec per config (equivalent spellings bucket
        # into one compiled sweep)
        "tiles": [TileSpec.parse(s).canonical()
                  for s in _strs(args.tiles)],
        "mean": _floats(args.means),
        "std": _floats(args.stds),
    }
    if any(b == 1 for b in axes["adc_bits"]):
        p.error("--adc-bits 1 is invalid (a symmetric quantizer with "
                "2^(bits-1)-1 == 0 levels); use 0 or >= 2")
    grid = codesign.expand_grid(axes)
    groups = codesign.group_static(grid)
    print(f"Co-design grid: {len(grid)} configs in {len(groups)} "
          f"compiled buckets "
          f"({' x '.join(f'{k}={len(v)}' for k, v in axes.items())})",
          flush=True)

    def build_solver(process, sigma, adc_bits, strategy, tiles):
        param = read_solver_param(args.solver)
        param.failure_pattern.type = "gaussian"
        param.random_seed = args.seed
        param.display = 0
        param.ClearField("test_interval")
        if sigma or adc_bits:
            param.rram_forward.sigma = float(sigma)
            param.rram_forward.adc_bits = int(adc_bits)
        if strategy != "none":
            kind, _, val = strategy.partition(":")
            if kind != "threshold":
                p.error(f"unknown strategy {strategy!r} (none or "
                        "threshold:T)")
            sp = param.failure_strategy.add()
            sp.type = "threshold"
            sp.threshold = float(val or 0.0)
        return Solver(param, fault_process=process, tile_spec=tiles)

    results = []
    results_path = os.path.join(out_dir, "results.jsonl")
    with open(results_path, "w") as rf:
        for key, cfgs in sorted(groups.items()):
            process, sigma, adc_bits, strategy, tiles = key
            means = [c["mean"] for c in cfgs]
            stds = [c["std"] for c in cfgs]
            t0 = time.perf_counter()
            solver = build_solver(process, sigma, adc_bits, strategy,
                                  tiles)
            with SweepRunner(solver, n_configs=len(cfgs), means=means,
                             stds=stds, pipeline_depth=0) as runner:
                losses, _ = runner.step(args.iters, chunk=args.chunk)
                broken = runner.broken_fractions()
            dt = time.perf_counter() - t0
            losses = np.ravel(np.asarray(losses, np.float64))
            for i, cfg in enumerate(cfgs):
                rec = dict(cfg)
                rec["loss"] = float(losses[i])
                rec["broken"] = float(broken[i])
                # hardware-cost proxy: a full-precision read
                # (adc_bits 0) costs a 32-bit converter, not a free one
                rec["adc_cost_bits"] = int(adc_bits) if adc_bits else 32
                rec["wall_seconds"] = round(dt, 3)
                results.append(rec)
                rf.write(json.dumps(rec) + "\n")
            print(f"  bucket process={process} sigma={sigma:g} "
                  f"adc_bits={adc_bits} strategy={strategy} "
                  f"tiles={tiles}: "
                  f"{len(cfgs)} lanes x {args.iters} iters in "
                  f"{dt:.1f} s (mean loss "
                  f"{float(np.nanmean(losses)):.4f})", flush=True)

    report = codesign.make_report(
        results, args.metric_x, args.metric_y,
        maximize_x=args.maximize_x, maximize_y=args.maximize_y,
        axes=axes)
    report_path = os.path.join(out_dir, "pareto_report.json")
    tmp = f"{report_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2)
    os.replace(tmp, report_path)
    print(f"Pareto front ({args.metric_x} vs {args.metric_y}): "
          f"{report['front_size']} of {report['evaluated']} configs "
          f"non-dominated ({report['dominated']} dominated); report "
          f"at {report_path}", flush=True)
    for rec in report["front"]:
        print("  front: "
              + ", ".join(f"{k}={rec[k]}" for k in
                          ("process", "sigma", "adc_bits", "strategy",
                           "tiles", "mean", "std"))
              + f" -> {args.metric_x}={rec.get(args.metric_x)}, "
                f"{args.metric_y}={rec.get(args.metric_y)}",
              flush=True)
    if report["degenerate"]:
        culprits = report.get("collapsed_axes") or []
        named = (f" collapsed axis(es): {', '.join(culprits)} — widen "
                 "those" if culprits else
                 " — widen --adc-bits / --processes / --sigmas / "
                 "--tiles")
        print("Front is DEGENERATE (a single point): the axes exposed "
              f"no tradeoff;{named}", flush=True)
        sys.exit(DEGENERATE_EXIT)
    return report


if __name__ == "__main__":
    main()

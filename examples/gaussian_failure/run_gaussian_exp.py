#!/usr/bin/env python
"""RRAM fault experiment runner — CLI-compatible with the reference's
examples/cifar10/gaussian_failure/run_gaussian_exp.py (same positional
mean/std/device and -t/-r/-g/--prob/--tag flags, same solver patching and
snapshot-dir layout, same tee'd log), plus the TPU-native --sweep mode that
replaces the one-process-per-config GPU fan-out (run_different_mean.sh)
with a single vmapped Monte-Carlo sweep.
"""
import argparse
import contextlib
import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)

from google.protobuf import text_format  # noqa: E402

from rram_caffe_simulation_tpu.proto import pb  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("mean", type=float)
    p.add_argument("std", type=float)
    p.add_argument("device_id", type=int,
                   help="kept for CLI parity; TPU devices come from the mesh")
    p.add_argument("-t", "--threshold", default=-1, type=float)
    p.add_argument("-r", "--remapping", default="",
                   help="<prune_order_file>[,<period>[,<start>]]")
    p.add_argument("-g", "--genetic", default="",
                   help="<prune_prototxt>,<prune_model>[,<switch_time>"
                        "[,<period>[,<start>]]]")
    p.add_argument("--tag", default="", help="suffix tag")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--prob", type=int, default=-1,
                   help="probability percentage for +-1 (0~100)")
    p.add_argument("-y", "--yes", action="store_true")
    p.add_argument("--template",
                   default=os.path.join(
                       ROOT, "models/cifar10_vgg11/"
                       "cifar10_vgg11_template.prototxt"))
    p.add_argument("--max-iter", type=int, default=0,
                   help="override template max_iter (testing)")
    p.add_argument("--sweep-means", default="",
                   help="comma list of lifetime means: train ALL configs "
                        "simultaneously via the vmapped fault axis")
    p.add_argument("--sweep-stds", default="")
    p.add_argument("--hw-sigma", type=float, default=0.0,
                   help="hardware-aware forward: relative conductance "
                        "noise on fault-target weights each read "
                        "(framework extension, RRAMForwardParameter)")
    p.add_argument("--conv-also", action="store_true",
                   help="fault Convolution params too (framework "
                        "extension; the reference faults only "
                        "InnerProduct, net.cpp:485-493)")
    p.add_argument("--compute-dtype", default="",
                   help="forward/backward dtype for --sweep-means runs "
                        "(e.g. bfloat16: ~1.6x sweep throughput; "
                        "masters/updates/fault state stay f32)")
    return p.parse_args(argv)


def build_solver_param(args) -> "pb.SolverParameter":
    """Patch the template exactly like the reference runner
    (run_gaussian_exp.py:45-103)."""
    message = pb.SolverParameter()
    with open(args.template) as f:
        text_format.Merge(f.read(), message)
    message.failure_pattern.type = "gaussian"
    message.failure_pattern.mean = args.mean
    message.failure_pattern.std = args.std
    message.device_id = args.device_id
    if args.max_iter:
        message.max_iter = args.max_iter
    if args.hw_sigma:
        message.rram_forward.sigma = args.hw_sigma
    if args.conv_also:
        message.failure_pattern.conv_also = True
    if args.threshold > 0:
        message.failure_strategy.add(type="threshold",
                                     threshold=args.threshold)
    if args.remapping:
        stra = args.remapping.split(",")
        sp = message.failure_strategy.add(type="remapping",
                                          prune_order_file=stra[0])
        if len(stra) > 1:
            sp.period = int(stra[1])
        if len(stra) > 2:
            sp.start = int(stra[2])
    if args.genetic:
        stra = args.genetic.split(",")
        sp = message.failure_strategy.add(type="genetic",
                                          prune_net_file=stra[0],
                                          prune_model_file=stra[1])
        if len(stra) > 2:
            sp.switch_time = int(stra[2])
        if len(stra) > 3:
            sp.period = int(stra[3])
        if len(stra) > 4:
            sp.start = int(stra[4])
    if args.prob >= 0:
        assert args.prob < 50
        fp = message.failure_pattern.failure_prob
        fp.neg = fp.pos = args.prob
        fp.zero = 100 - 2 * args.prob
    return message


class Tee:
    def __init__(self, path):
        self.f = open(path, "w")

    def write(self, s):
        sys.__stdout__.write(s)
        self.f.write(s)

    def flush(self):
        sys.__stdout__.flush()
        self.f.flush()


def main(argv=None):
    args = parse_args(argv)
    strategy_suffix = ""
    if args.threshold > 0:
        strategy_suffix += f"_threshold_{args.threshold}"
    if args.remapping:
        strategy_suffix += ("_remapping_" + os.path.basename(
            args.remapping.split(",")[0]))
    if args.genetic:
        # the reference embedded the raw -g string (its files were local
        # names); basename the paths so the snapshot dir stays valid
        strategy_suffix += "_genetic_" + ",".join(
            os.path.basename(p) for p in args.genetic.split(","))
    message = build_solver_param(args)

    snapshot_prefix = (f"snapshot_{args.mean}_{args.std}"
                       f"{strategy_suffix}{args.tag}")
    if os.path.exists(snapshot_prefix):
        if not args.yes:
            yes = input(f"{snapshot_prefix} already exists, remove? (y/n): ")
            if yes.lower() not in {"y", "yes"}:
                sys.exit()
        shutil.rmtree(snapshot_prefix)
    os.makedirs(snapshot_prefix)
    message.snapshot_prefix = snapshot_prefix + "/"

    solver_dir = os.path.join(HERE, "solvers")
    os.makedirs(solver_dir, exist_ok=True)
    solver_fname = os.path.join(
        solver_dir,
        f"solver_{args.mean}_{args.std}{strategy_suffix}{args.tag}"
        ".prototxt")
    with open(solver_fname, "w") as f:
        f.write(text_format.MessageToString(message))
    print(f"New solver prototxt write to {solver_fname}.")

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from rram_caffe_simulation_tpu.solver import Solver

    tee = Tee(os.path.join(snapshot_prefix, "log"))
    with contextlib.redirect_stdout(tee):
        # log the solver config so plot_pic-style scrapers find
        # test_interval (plot_pic.py:16)
        print(text_format.MessageToString(message))
        if args.sweep_means:
            from rram_caffe_simulation_tpu.parallel import SweepRunner
            import numpy as np
            means = [float(x) for x in args.sweep_means.split(",")]
            stds = ([float(x) for x in args.sweep_stds.split(",")]
                    if args.sweep_stds else None)
            solver = Solver(message,
                            compute_dtype=args.compute_dtype or None)
            # SweepRunner inherits the solver's compute_dtype
            runner = SweepRunner(solver, n_configs=len(means),
                                 means=np.asarray(means, np.float32),
                                 stds=(np.asarray(stds, np.float32)
                                       if stds else None))
            interval = message.display or 100
            for start in range(0, message.max_iter, interval):
                loss, _ = runner.step(min(interval,
                                          message.max_iter - start))
                fracs = runner.broken_fractions()
                for ci, m in enumerate(means):
                    print(f"config {ci} (mean={m:g}): Iteration "
                          f"{runner.iter}, loss = {loss[ci]:.5g}, "
                          f"broken = {fracs[ci]:.4f}")
        else:
            solver = Solver(message)
            solver.solve()
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""The remapping strategy in its designed-for setting: PRUNED deployment.

The fork's remapping thesis (RemappingFailureStrategy, reference
strategy.cpp:89-137 + usage.md workflow): during training, periodically
park the most-PRUNABLE logical neurons (per a magnitude-prune ranking)
on the most-BROKEN physical rows, so the important sub-network trains on
healthy cells. The payoff is not dense accuracy — RESULTS.md shows
remapping losing densely, because the sacrificial neurons keep injecting
stuck-cell garbage — it is the *pruned deployment*: remove the prunable
neurons at deploy time and the parked corruption leaves with them.

This script measures exactly that, end to end on the LeNet/digits task
at the r3 operating point (lifetimes N(3e5, 8e4), stuck prob 5/90/5):

  1. train unmitigated and remapped runs side by side;
  2. deploy both PRUNED: zero the K most-prunable logical neurons —
     for the unmitigated run those are the prune_order tail rows (the
     physical layout never moved); for the remapped run they sit, by
     the strategy's permutation invariant, on the most-broken physical
     slots (sort_fc_neurons of the final fault state);
  3. report dense vs pruned test accuracy for both.

    python examples/gaussian_failure/pruned_deploy_eval.py \
        [--iters 3000] [--prune-k 300]
"""
import argparse
import copy
import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)


def build_solver(args, remapping: bool, tmp_tag: str,
                 tracked: bool = False):
    from google.protobuf import text_format
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver

    param = pb.SolverParameter()
    with open(os.path.join(ROOT, "models/lenet/"
                           "lenet_digits_solver.prototxt")) as f:
        text_format.Merge(f.read(), param)
    param.net = "models/lenet/lenet_train_test_lmdb.prototxt"
    param.max_iter = args.iters
    param.display = 500
    param.test_interval = 10 ** 9          # eval is explicit, below
    param.snapshot = 0
    param.random_seed = 11
    param.snapshot_prefix = os.path.join(
        args.out, f"pruned_deploy_{tmp_tag}")
    fp = param.failure_pattern
    fp.type = "gaussian"
    fp.mean = args.mean
    fp.std = args.std
    fp.failure_prob.neg = 5
    fp.failure_prob.zero = 90
    fp.failure_prob.pos = 5
    if remapping:
        st = param.failure_strategy.add()
        st.type = "remapping"
        st.start = 0
        st.period = 100
        st.prune_order_file = os.path.join(HERE, "prune_order_lenet.txt")
        st.track_identity = tracked
    return Solver(param)


def prune_hidden(params, fc_pairs, slots):
    """Deploy-time removal of hidden neurons `slots` of the (single)
    LeNet hidden FC group: zero ip1 rows + bias and ip2 columns —
    exactly what instantiating the pruned sub-network does."""
    out = {ln: list(v) for ln, v in params.items()}
    (w1, b1), (w2, _) = fc_pairs
    l1, s1 = w1.rsplit("/", 1)
    l2, s2 = w2.rsplit("/", 1)
    w = np.array(out[l1][int(s1)])
    w[slots, :] = 0.0
    out[l1][int(s1)] = w
    if b1 is not None:
        lb, sb = b1.rsplit("/", 1)
        b = np.array(out[lb][int(sb)])
        b[slots] = 0.0
        out[lb][int(sb)] = b
    v = np.array(out[l2][int(s2)])
    v[:, slots] = 0.0
    out[l2][int(s2)] = v
    return out


def test_accuracy(solver, params) -> float:
    saved = solver.params
    try:
        solver.params = params
        scores = solver.test(0)
    finally:
        solver.params = saved
    for name, val in scores.items():
        if "accuracy" in name.lower() or name == "accuracy":
            return float(np.ravel(val)[0])
    raise KeyError(f"no accuracy output in {list(scores)}")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=3000,
                   help="~50%% of cells break by 3000 at the 3e5/8e4 "
                        "operating point (decrement 100/write)")
    p.add_argument("--mean", type=float, default=3e5)
    p.add_argument("--std", type=float, default=8e4)
    p.add_argument("--prune-k", type=int, default=300,
                   help="hidden neurons pruned at deployment (of 500; "
                        "300 = the 0.6 prune ratio of the ordering file)")
    p.add_argument("--out", default=os.path.join(HERE, "logs"))
    args = p.parse_args(argv)

    os.chdir(ROOT)
    os.makedirs(args.out, exist_ok=True)
    from rram_caffe_simulation_tpu.fault.strategies import sort_fc_neurons

    prune_order = np.loadtxt(
        os.path.join(HERE, "prune_order_lenet.txt"), dtype=int)
    K = args.prune_k
    logical_prunable = prune_order[-K:]     # most-prunable tail

    rows = {}
    for tag, remap, tracked in (("unmitigated", False, False),
                                ("remapping", True, False),
                                ("remapping_tracked", True, True)):
        solver = build_solver(args, remapping=remap, tmp_tag=tag,
                              tracked=tracked)
        solver.step_fused(args.iters, chunk=100)
        dense = test_accuracy(solver, solver.params)

        if tracked:
            # the slot map says exactly where each logical neuron lives
            sol = np.asarray(solver.fault_state["remap_slots"]["0"])
            slots = sol[logical_prunable]
        elif remap:
            # reference semantics: the strategy claims to park the
            # prunable logical tail on the most-broken physical slots;
            # deployment prunes there
            order = np.asarray(sort_fc_neurons(
                solver.fault_state, [w for w, _ in solver.fc_pairs])[0])
            slots = order[-K:]
        else:
            slots = logical_prunable        # layout never moved
        pruned_params = prune_hidden(solver.params, solver.fc_pairs,
                                     slots)
        pruned = test_accuracy(solver, pruned_params)

        # most charitable deployment: magnitude-prune 60% of ip1 CELLS
        # of the run's OWN final weights (stuck-0 cells self-select into
        # the pruned set; this is the per-cell analogue of the thesis)
        w1key = solver.fc_pairs[0][0]
        l1, s1 = w1key.rsplit("/", 1)
        cellp = {ln: list(v) for ln, v in solver.params.items()}
        w = np.array(cellp[l1][int(s1)])
        thresh = np.quantile(np.abs(w), 0.6)
        w[np.abs(w) <= thresh] = 0.0
        cellp[l1][int(s1)] = w
        cell_pruned = test_accuracy(solver, cellp)

        life = np.asarray(
            solver.fault_state["lifetimes"][solver.fc_pairs[0][0]])
        broken_frac = float((life <= 0).mean())
        rows[tag] = {"dense": round(dense, 4),
                     "pruned": round(pruned, 4),
                     "cell_pruned": round(cell_pruned, 4),
                     "ip1_broken_frac": round(broken_frac, 3)}
        print(f"{tag}: dense {dense:.4f}  pruned-deploy {pruned:.4f}  "
              f"cell-pruned {cell_pruned:.4f}  "
              f"(ip1 broken {broken_frac:.1%})", flush=True)

    rec = {"iters": args.iters, "mean": args.mean, "std": args.std,
           "prune_k": K, **rows}
    print(json.dumps(rec), flush=True)
    return rec


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Minimal sweep-service client: submit a small fault sweep to a local
service and tail its per-request metrics stream.

Start a service first (in another terminal; any solver with a pinned
random_seed, a gaussian failure_pattern, and a Data layer)::

    python -m rram_caffe_simulation_tpu.serve \
        --solver models/cifar10_quick/cifar10_quick_lmdb_solver.prototxt \
        --service-dir /tmp/sweep-svc --lanes 8 --chunk 10

then::

    python examples/gaussian_failure/serve_demo.py \
        --dir /tmp/sweep-svc --mean 500 --std 100 --configs 4 \
        --iters 100 --tenant demo

The script submits one request over the Unix-socket front door (or the
durable spool when the socket is down), prints every lifecycle record
from the request's own `requests/<id>.jsonl` stream as it lands —
submitted -> admitted -> started -> config_done* -> completed/failed —
and exits 0 on completed, 1 otherwise. The stream is per-request: a
tenant tails their request without reading anyone else's records.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

from rram_caffe_simulation_tpu.serve import ServeClient  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--dir", required=True,
                   help="the service's --service-dir")
    p.add_argument("--mean", type=float, default=500.0,
                   help="cell-lifetime mean for every config")
    p.add_argument("--std", type=float, default=100.0,
                   help="cell-lifetime std for every config")
    p.add_argument("--configs", type=int, default=4,
                   help="Monte-Carlo configs in the request")
    p.add_argument("--iters", type=int, default=0,
                   help="training iterations per config (0 = the "
                        "service default)")
    p.add_argument("--tenant", default="demo")
    p.add_argument("--timeout", type=float, default=3600.0,
                   help="give up tailing after this many seconds")
    args = p.parse_args(argv)

    client = ServeClient(args.dir)
    req = {"tenant": args.tenant,
           "configs": [{"mean": args.mean, "std": args.std}
                       for _ in range(args.configs)]}
    if args.iters:
        req["iters"] = args.iters
    out = client.submit(req)
    rid = out["id"]
    where = "front door" if client.ping() else \
        "spool (service down — it will pick the request up)"
    print(f"submitted {rid} via the {where}", flush=True)
    if out.get("projected_s"):
        print(f"projected turnaround ~{out['projected_s']:.0f} s",
              flush=True)

    last = None
    for rec in client.tail(rid, timeout_s=args.timeout):
        print(json.dumps(rec), flush=True)
        last = rec
    if last is None or last.get("event") not in ("completed", "failed",
                                                 "rejected"):
        print(f"gave up after {args.timeout:g} s; check later with: "
              f"python -m rram_caffe_simulation_tpu.serve.serve_client "
              f"--dir {args.dir} status {rid}", file=sys.stderr)
        return 1
    if last["event"] == "completed":
        result = client.result(rid)
        print("per-config results:")
        for cfg, v in sorted(result.get("results", {}).items(),
                             key=lambda kv: int(kv[0])):
            print(f"  config {cfg}: {v['status']}, final loss "
                  f"{v['loss']:.6g}, broken fraction "
                  f"{v['broken']:.4f}, {v['attempts']} attempt(s)")
        return 0
    print(f"request {rid} ended {last['event']}: "
          f"{last.get('reason', 'no diagnosis')}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Grid sweeps over RRAM experiment knobs — one runner replacing the
reference's per-grid shell scripts (run_different_mean.sh,
run_different_mean_var.sh, run_different_prob.sh, run_threshold.sh,
run_different_th.sh: each fanned configs over GPUs as processes).

- mean / std grids train every config SIMULTANEOUSLY on the vmapped
  Monte-Carlo axis (delegates to run_gaussian_exp --sweep-*).
- prob / threshold grids change the stuck-value draw or add a per-config
  strategy — config-static structure the vmapped axis doesn't cover — so
  they run through parallel.sweep.sequential_sweep (one Solver per
  config, the reference's process-per-config semantics without the
  process boundary) and print a result table.

    python run_sweeps.py mean 1e8 3e7 --values 5e7,1e8,2e8
    python run_sweeps.py prob 1e8 3e7 --values 2,5,10 --max-iter 2000
    python run_sweeps.py threshold 1e8 3e7 --values 0.01,0.05,0.1
"""
import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)
sys.path.insert(0, HERE)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("kind", choices=["mean", "std", "prob", "threshold"])
    p.add_argument("mean", type=float)
    p.add_argument("std", type=float)
    p.add_argument("--values", required=True,
                   help="comma-separated grid values")
    p.add_argument("--max-iter", type=int, default=0)
    p.add_argument("--eval", action="store_true",
                   help="run the test net after each sequential config")
    p.add_argument("--template",
                   default=os.path.join(
                       ROOT, "models/cifar10_vgg11/"
                       "cifar10_vgg11_template.prototxt"))
    p.add_argument("--tag", default="")
    args = p.parse_args(argv)
    values = [float(v) for v in args.values.split(",")]

    if args.kind in ("mean", "std"):
        from run_gaussian_exp import main as run
        run_args = [str(args.mean), str(args.std), "0", "-y",
                    "--template", args.template,
                    "--tag", args.tag or f"_{args.kind}sweep"]
        if args.kind == "mean":
            run_args += ["--sweep-means",
                         ",".join(str(v) for v in values)]
        else:
            run_args += ["--sweep-means",
                         ",".join(str(args.mean) for _ in values),
                         "--sweep-stds", ",".join(str(v) for v in values)]
        if args.max_iter:
            run_args += ["--max-iter", str(args.max_iter)]
        return run(run_args)

    # prob / threshold: per-config structure -> sequential driver
    from google.protobuf import text_format
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.parallel.sweep import sequential_sweep

    sp = pb.SolverParameter()
    with open(args.template) as f:
        text_format.Merge(f.read(), sp)
    sp.failure_pattern.type = "gaussian"
    sp.failure_pattern.mean = args.mean
    sp.failure_pattern.std = args.std
    sp.snapshot = 0
    sp.display = 0
    sp.ClearField("test_interval")
    if args.max_iter:
        sp.max_iter = args.max_iter
    iters = sp.max_iter
    key = args.kind
    configs = [{key: (int(v) if key == "prob" else v)} for v in values]
    os.chdir(ROOT)
    results = sequential_sweep(sp, configs, iters,
                               eval_iters=1 if args.eval else 0)
    print(f"{key:>10s}  {'loss':>10s}  {'broken':>8s}  scores")
    for rec in results:
        scores = " ".join(f"{k}={v:.4f}"
                          for k, v in rec.get("scores", {}).items())
        print(f"{rec['config'][key]:>10}  {rec['loss']:>10.4f}  "
              f"{rec.get('broken', 0.0):>8.4f}  {scores}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

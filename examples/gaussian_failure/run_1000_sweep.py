"""The MEASURED 1000-config north-star run (BASELINE: 1000-config
5k-iter CIFAR-10-quick sweep < 10 min on a v4-8).

One v5e chip can hold ~500 CIFAR-quick fault configs in HBM at batch
100 (1000 at once needs ~21 GB), and the config axis is embarrassingly
parallel — so the single-chip measurement runs the 1000 configs as
sequential SweepRunner groups and reports TOTAL wall time, which is
exactly what 2 chips would do concurrently (and what 8 chips do at 125
configs each for the v4-8 figure; the dryrun certifies the multi-chip
mesh compiles/executes).

Host/device overlap (the async execution layer): each runner runs with
a pipelined dispatcher (`--pipeline-depth`), and consecutive resident
groups are OVERLAPPED — while group A executes, a background thread
draws group B's fault state, places it, decodes/reuses the dataset and
AOT-compiles the chunk function (GroupPrefetcher + precompile_chunk),
so group B starts hot the moment A finishes. `--no-overlap` restores
the serial cold starts for comparison; the JSON record reports the
hidden setup seconds per group.

    python examples/gaussian_failure/run_1000_sweep.py \
        [--configs 1000] [--group 500] [--iters 5000] [--chunk 50]
"""
import argparse
import json
import math
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--configs", type=int, default=1000)
    p.add_argument("--group", type=int, default=1000,
                   help="configs resident per runner (with --block, all "
                        "1000 fit one chip — r4; use 500 with block 0 "
                        "to reproduce the r3 two-group run)")
    p.add_argument("--block", type=int, default=250,
                   help="configs computed per sequential lax.map block "
                        "inside the step (activation memory scales with "
                        "the block, resident state with the group); 0 "
                        "disables blocking")
    p.add_argument("--iters", type=int, default=5000)
    p.add_argument("--chunk", type=int, default=50)
    p.add_argument("--mean", type=float, default=1e8)
    p.add_argument("--std", type=float, default=3e7)
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="in-flight chunks whose host bookkeeping the "
                        "consumer thread hides; 0 = synchronous "
                        "bookkeeping at every chunk boundary")
    p.add_argument("--no-overlap", action="store_true",
                   help="build each group's runner serially instead of "
                        "prefetching group N+1 while group N executes")
    args = p.parse_args(argv)

    os.chdir(REPO)
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.parallel import (GroupPrefetcher,
                                                    SweepRunner)
    from rram_caffe_simulation_tpu.utils.io import read_solver_param

    groups = [args.group] * (args.configs // args.group)
    if args.configs % args.group:
        groups.append(args.configs % args.group)

    def build_runner(gi, n_cfg):
        param = read_solver_param(
            "models/cifar10_quick/cifar10_quick_lmdb_solver.prototxt")
        param.failure_pattern.type = "gaussian"
        param.failure_pattern.mean = args.mean
        param.failure_pattern.std = args.std
        param.random_seed = 7 + gi
        param.display = 0
        param.ClearField("test_interval")
        solver = Solver(param, compute_dtype="bfloat16")
        # per-group block: groups at or under the block need no
        # blocking (they already fit the activation budget); an
        # indivisible larger remainder falls back to its gcd rather
        # than crashing after earlier groups burned their wall-clock
        if not args.block or n_cfg <= args.block:
            block = 0
        elif n_cfg % args.block == 0:
            block = args.block
        else:
            block = math.gcd(n_cfg, args.block)
        return SweepRunner(solver, n_configs=n_cfg, config_block=block,
                           precompile_chunk=args.chunk,
                           pipeline_depth=args.pipeline_depth)

    t_total = time.perf_counter()
    done = 0
    blocks_used, overlap_s, host_blocked_s = [], [], []
    prefetch = GroupPrefetcher()
    runner = build_runner(0, groups[0])
    for gi, n_cfg in enumerate(groups):
        if not args.no_overlap and gi + 1 < len(groups):
            # group B's whole setup (fault draw, placement, dataset,
            # AOT compile) runs behind group A's execution
            prefetch.start(build_runner, gi + 1, groups[gi + 1])
        t0 = time.perf_counter()
        runner.step(args.iters, chunk=args.chunk)
        broken = runner.broken_fractions()
        dt = time.perf_counter() - t0
        blocks_used.append(runner.config_block)
        pipe = runner.setup_record().get("pipeline", {})
        overlap_s.append(round(pipe.get("setup_overlap_seconds", 0.0), 2))
        host_blocked_s.append(round(pipe.get("host_blocked_seconds",
                                             0.0), 4))
        runner.close()
        done += n_cfg
        print(f"group {gi}: {n_cfg} configs x {args.iters} iters in "
              f"{dt / 60:.2f} min (broken mean {broken.mean():.3f}); "
              f"{done}/{args.configs} done", flush=True)
        if gi + 1 < len(groups):
            runner = (build_runner(gi + 1, groups[gi + 1])
                      if args.no_overlap else prefetch.take())
    total_min = (time.perf_counter() - t_total) / 60
    rec = {
        "configs": args.configs,
        "iters_per_config": args.iters,
        "batch": 100,
        "groups": groups,
        "config_block": blocks_used,
        "wall_minutes_one_chip": round(total_min, 2),
        "configs_per_hour_one_chip": round(args.configs
                                           / (total_min / 60), 1),
        "v4_8_projection_minutes": round(total_min / 8, 2),
        "compute_dtype": "bfloat16",
        "pipeline_depth": args.pipeline_depth,
        "overlapped_groups": not args.no_overlap,
        # per-group async accounting: setup seconds hidden behind the
        # previous group's execution, and the dispatcher's host-blocked
        # seconds across the group's chunk dispatches
        "group_setup_overlap_seconds": overlap_s,
        "host_blocked_seconds": host_blocked_s,
    }
    print(json.dumps(rec), flush=True)
    return rec


if __name__ == "__main__":
    main()

"""The MEASURED 1000-config north-star run (BASELINE: 1000-config
5k-iter CIFAR-10-quick sweep < 10 min on a v4-8).

One v5e chip can hold ~500 CIFAR-quick fault configs in HBM at batch
100 (1000 at once needs ~21 GB), and the config axis is embarrassingly
parallel — so the single-chip measurement runs the 1000 configs as
sequential SweepRunner groups and reports TOTAL wall time, which is
exactly what 2 chips would do concurrently (and what 8 chips do at 125
configs each for the v4-8 figure; the dryrun certifies the multi-chip
mesh compiles/executes).

Host/device overlap (the async execution layer): each runner runs with
a pipelined dispatcher (`--pipeline-depth`), and consecutive resident
groups are OVERLAPPED — while group A executes, a background thread
draws group B's fault state, places it, decodes/reuses the dataset and
AOT-compiles the chunk function (GroupPrefetcher + precompile_chunk),
so group B starts hot the moment A finishes. `--no-overlap` restores
the serial cold starts for comparison; the JSON record reports the
hidden setup seconds per group.

Durability (the sweep-durability layer): `--run-dir DIR` makes the run
survive the scheduler — DIR gets a manifest, a JSONL completion
journal (one fsynced line per finished group), per-group fault-state
.npz archives, per-group metrics JSONL, and periodic in-flight group
checkpoints (`--checkpoint-every`, full SweepRunner.checkpoint: params
+ histories + fault state + quarantine + RNG roots + the self-healing
work queue). A SIGTERM or SIGINT drains the async pipeline, writes a
final checkpoint within `--grace-seconds`, and exits with the distinct
code 75 (EX_TEMPFAIL = "preempted, retry me"). `--resume DIR` then
skips every journaled group and restores the in-flight one mid-run;
the resumed sweep is BIT-EXACT against an uninterrupted one
(scripts/check_resume_equivalence.py is the CI guard).

Self-healing (the completion contract): every group runs with
SweepRunner.enable_self_healing — a config whose lane goes NaN has its
attempt voided and is retried (`--max-retries`, `--retry-backoff`
iterations of escalating backoff; recovery restores the config's last
good checkpointed slice when one exists, else re-initializes fresh) in
a reclaimed lane, so the run ENDS only when every requested config is
`completed` or `failed` with a triage diagnosis. The final ledger is
written to `<run-dir>/sweep_report.json` and the exit code is the
contract: 0 = every config completed, 65 (EX_DATAERR) = some configs
permanently failed (partial results, diagnoses in the report), 75
(EX_TEMPFAIL) = preempted or stalled mid-run (resume me).
`scripts/check_lane_reclamation.py` is the CI guard.

Pod-scale (the config axis sharded across a real mesh): launch ONE
process per host with the same command plus `--num-processes/
--process-id/--coordinator` (TPU pods autodetect all three — just pass
`--multihost`). The config axis of every group then lays across ALL
hosts' chips as one GSPMD program (make_mesh sorts devices by
(process_index, id), so every host assembles the identical mesh);
process 0 owns the journal/manifest/report, metrics land in
per-process `metrics_gN.pP.jsonl` files, group checkpoints become v4
DISTRIBUTED directories (per-process shard files under one
manifest.json), and a SIGTERM delivered to ANY process drains ALL of
them at the same chunk boundary (the preempt flag is agreed via a
tiny allgather at every poll slice) — every process exits 75 and
`--resume` restores onto the SAME or a DIFFERENT topology bit-exactly
(the v4 resharding contract; scripts/check_pod_sweep.py is the CI
guard). The run directory must be a filesystem every process sees.

    python examples/gaussian_failure/run_1000_sweep.py \
        [--configs 1000] [--group 500] [--iters 5000] [--chunk 50] \
        [--run-dir sweeps/run0]          # durable
    python examples/gaussian_failure/run_1000_sweep.py --resume sweeps/run0
"""
import argparse
import json
import math
import os
import shutil
import signal
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)

#: Exit code of a preempted (SIGTERM/SIGINT) or stalled durable run —
#: EX_TEMPFAIL, the sysexits.h "transient failure, retry" code, distinct
#: from both success and a crash so schedulers/wrappers can requeue with
#: --resume.
PREEMPTED_EXIT = 75

#: Exit code of a run that FINISHED but with permanently failed configs
#: (retry budget exhausted) — EX_DATAERR: the results are partial and
#: sweep_report.json carries a per-config triage diagnosis. Monte-Carlo
#: statistics built from this run must account for the failed draws.
PARTIAL_EXIT = 65

#: Manifest keys that pin the run's math; --resume restores them so a
#: resumed run cannot silently diverge from the original configuration.
MANIFEST_ARGS = ("configs", "group", "block", "iters", "chunk", "mean",
                 "std", "pipeline_depth", "solver", "checkpoint_every",
                 "max_retries", "retry_backoff", "process")

#: the fault-process spec every pre-process-registry run dir trained
#: under (and the --process default)
DEFAULT_PROCESS = "endurance_stuck_at"


def _journal_append(path: str, rec: dict):
    """One fsynced JSONL line — the journal must survive the very
    SIGKILL the checkpoint is racing."""
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _read_journal(path: str):
    recs = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    recs.append(json.loads(line))
    return recs


def _ckpt_ready(path: str) -> bool:
    """True when a usable checkpoint exists at `path`: the single-file
    layout, or a v4 distributed directory whose manifest.json commit
    record landed (a directory without one is an aborted write)."""
    if os.path.isdir(path):
        return os.path.exists(os.path.join(path, "manifest.json"))
    return os.path.exists(path)


def _ckpt_iter(path: str) -> int:
    if os.path.isdir(path):
        with open(os.path.join(path, "manifest.json")) as f:
            return int(json.load(f)["meta"]["iter"])
    with np.load(path) as z:
        meta = json.loads(bytes(bytearray(z["__meta__"])).decode())
    return int(meta["iter"])


def _ckpt_remove(path: str):
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
    else:
        try:
            os.remove(path)
        except OSError:
            pass


def _truncate_metrics(path: str, upto_iter: int):
    """Drop metrics records the restored checkpoint has NOT replayed.
    A stale periodic checkpoint plus an exhausted grace budget leaves
    records newer than the saved state; appending after restore would
    then duplicate the re-run chunks. A chunk record's `iter` is its
    LAST iteration, so everything >= the checkpoint iteration goes."""
    if not os.path.exists(path):
        return
    kept = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            it = rec.get("iter")
            if not isinstance(it, int) or it < upto_iter:
                kept.append(line)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for line in kept:
            f.write(line + "\n")
    os.replace(tmp, path)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--configs", type=int, default=1000)
    p.add_argument("--group", type=int, default=1000,
                   help="configs resident per runner (with --block, all "
                        "1000 fit one chip — r4; use 500 with block 0 "
                        "to reproduce the r3 two-group run)")
    p.add_argument("--block", type=int, default=250,
                   help="configs computed per sequential lax.map block "
                        "inside the step (activation memory scales with "
                        "the block, resident state with the group); 0 "
                        "disables blocking")
    p.add_argument("--iters", type=int, default=5000)
    p.add_argument("--chunk", type=int, default=50)
    p.add_argument("--mean", type=float, default=1e8)
    p.add_argument("--std", type=float, default=3e7)
    p.add_argument("--solver", default=(
        "models/cifar10_quick/cifar10_quick_lmdb_solver.prototxt"),
        help="solver prototxt the per-group Solver is built from "
             "(failure pattern / seed / display are overridden here)")
    p.add_argument("--process", default=None,
                   help="fault-process stack spec (fault/processes/ "
                        "registry; default endurance_stuck_at — the "
                        "reference model). Pinned in the run-dir "
                        "manifest: --resume refuses a mismatched "
                        "process instead of replaying the wrong "
                        "physics")
    p.add_argument("--engine", default="jax",
                   choices=("jax", "pallas", "auto"),
                   help="hardware-aware crossbar engine (ENGINE "
                        "MATRIX, fault/hw_aware.py); 'pallas' runs "
                        "config-sharded under the mesh via shard_map "
                        "and falls back LOUDLY where it cannot — the "
                        "resolution lands in sweep_report.json")
    p.add_argument("--dtype-policy", default="",
                   help="quantized sweep compute ('' | ternary | "
                        "int8): fault-target weight reads through the "
                        "quantize_ste ADC grid — also what arms the "
                        "pallas kernel at sigma == 0")
    p.add_argument("--packed-state", action="store_true",
                   help="bit-packed fault banks (fault/packed.py)")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="in-flight chunks whose host bookkeeping the "
                        "consumer thread hides; 0 = synchronous "
                        "bookkeeping at every chunk boundary")
    p.add_argument("--no-overlap", action="store_true",
                   help="build each group's runner serially instead of "
                        "prefetching group N+1 while group N executes")
    p.add_argument("--run-dir", default="",
                   help="durable run directory: manifest + completion "
                        "journal + per-group fault/metrics files + "
                        "in-flight checkpoints; SIGTERM/SIGINT then "
                        "checkpoint-and-exit(75) instead of dying")
    p.add_argument("--resume", default="",
                   help="resume a durable run directory: journaled "
                        "groups are skipped, the in-flight group is "
                        "restored mid-run (bit-exact vs uninterrupted)")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="iterations between periodic in-flight group "
                        "checkpoints (rounded up to a --chunk "
                        "multiple); 0 = checkpoint only on preemption")
    p.add_argument("--grace-seconds", type=float, default=30.0,
                   help="preemption grace budget: the final checkpoint "
                        "is only attempted while this much time "
                        "remains since the signal landed")
    p.add_argument("--max-retries", type=int, default=1,
                   help="per-config retry budget: how many times a "
                        "quarantined (NaN) config is re-seeded into a "
                        "reclaimed lane before it is permanently "
                        "failed with a diagnosis")
    p.add_argument("--retry-backoff", type=int, default=0,
                   help="iteration backoff per retry: attempt k waits "
                        "k * this many iterations before its lane is "
                        "re-seeded (escalating)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="seconds of consumer-heartbeat silence before "
                        "a stalled chunk aborts the run with a "
                        "best-effort checkpoint and exit 75 instead of "
                        "hanging; 0 = disabled")
    p.add_argument("--trace", action="store_true",
                   help="arm the host span tracer (observe/spans.py): "
                        "dispatch/consume/drain/heal/checkpoint spans "
                        "as schema-validated `span` records in each "
                        "group's metrics stream, per-process Perfetto "
                        "trace files under <run-dir>/trace/ and — on a "
                        "clean finish — one merged timeline "
                        "(trace/merged.trace.json) covering every "
                        "process's dispatcher and consumer threads")
    p.add_argument("--inject-nan", default="",
                   help="TEST HOOK (check_lane_reclamation.py): "
                        "'CFG@ITER' poisons global config CFG's params "
                        "with NaN at the first step boundary at/after "
                        "iteration ITER; append ':always' to re-poison "
                        "every attempt (exercises the permanent-"
                        "failure path)")
    p.add_argument("--multihost", action="store_true",
                   help="pod mode: jax.distributed.initialize before "
                        "anything touches the backend (TPU pods "
                        "autodetect coordinator/count/id from the "
                        "runtime; elsewhere pass the three flags "
                        "below or the COORDINATOR_ADDRESS / "
                        "NUM_PROCESSES / PROCESS_ID env vars). The "
                        "config axis of every group then shards over "
                        "ALL hosts' chips")
    p.add_argument("--coordinator", default=None,
                   help="coordinator address host:port (implies "
                        "--multihost)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total process count (implies --multihost)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's id, 0-based (implies "
                        "--multihost; process 0 owns the journal/"
                        "manifest/report)")
    args = p.parse_args(argv)

    # pod mode: the cluster must initialize BEFORE jax (even
    # jax.devices()) initializes XLA — keep this above every
    # rram_caffe_simulation_tpu import that could touch the backend
    multi = (args.multihost or args.coordinator is not None
             or args.num_processes is not None
             or args.process_id is not None)
    if multi:
        from rram_caffe_simulation_tpu.parallel import multihost
        multihost.initialize(args.coordinator, args.num_processes,
                             args.process_id)
    import jax
    from rram_caffe_simulation_tpu.parallel import multihost
    nproc = jax.process_count()
    pid = jax.process_index()
    primary = pid == 0
    if nproc > 1 and args.stall_timeout:
        p.error("--stall-timeout is single-process (the emergency "
                "checkpoint it writes is a collective the stalled "
                "peers would never join)")

    def _any_preempt(preempt: dict) -> bool:
        """Global preemption agreement: a signal delivered to ANY
        process preempts ALL of them at this same poll boundary.
        Collective — every process calls at the same control-flow
        points (free single-process)."""
        got = multihost.process_any(bool(preempt))
        if got and not preempt:
            preempt.setdefault("signal", "PEER")
            preempt.setdefault("t", time.monotonic())
        return got

    os.chdir(REPO)
    run_dir = os.path.abspath(args.resume or args.run_dir) \
        if (args.resume or args.run_dir) else ""
    resuming = bool(args.resume)
    manifest_path = os.path.join(run_dir, "manifest.json") if run_dir \
        else ""
    journal_path = os.path.join(run_dir, "journal.jsonl") if run_dir \
        else ""
    if resuming:
        with open(manifest_path) as f:
            manifest = json.load(f)
        # fault-process pin: the manifest names the physics the run
        # trained under; an explicit conflicting --process on resume is
        # refused here (and the checkpoint meta's own v5 pin would
        # refuse too) rather than silently replaying the wrong model.
        # Specs compare CANONICALIZED (stack order / param formatting
        # normalized) so an equivalent spelling resumes fine; an
        # unparseable spec falls back to a raw-string compare and lets
        # the Solver raise the parse diagnosis.
        pinned = manifest.get("process") or DEFAULT_PROCESS

        def _canon(spec):
            try:
                from rram_caffe_simulation_tpu.fault.processes import \
                    FaultSpec
                return FaultSpec.parse(spec).canonical()
            except Exception:
                return str(spec).strip()

        if args.process is not None \
                and _canon(args.process) != _canon(pinned):
            p.error(
                f"--resume {run_dir} was trained under fault process "
                f"{pinned!r} (manifest pin) but --process requests "
                f"{args.process!r}; resume without --process, or with "
                "the pinned spec")
        for key in MANIFEST_ARGS:
            # .get: manifests written before a flag existed resume with
            # the current default (e.g. pre-self-healing run dirs have
            # no max_retries/retry_backoff)
            setattr(args, key, manifest.get(key, getattr(args, key)))
        print(f"Resuming {run_dir}: manifest restored "
              f"({args.configs} configs, groups of {args.group}, "
              f"{args.iters} iters, process "
              f"{args.process or DEFAULT_PROCESS})", flush=True)
    if args.process is None:
        args.process = DEFAULT_PROCESS

    from rram_caffe_simulation_tpu.observe import JsonlSink
    from rram_caffe_simulation_tpu.observe import spans as obs_spans
    from rram_caffe_simulation_tpu.parallel import (GroupPrefetcher,
                                                    SweepRunner)
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.utils.io import read_solver_param

    # one tracer for the WHOLE run (all groups share it, so the merged
    # timeline shows group boundaries and the prefetched builds that
    # overlap them); each runner drains it into its own group's
    # metrics stream at step() returns
    tracer = (obs_spans.SpanTracer(process_index=pid) if args.trace
              else None)
    if tracer is not None:
        tracer.set_thread_role("dispatcher")

    def _write_trace():
        """Per-process Perfetto export under <run-dir>/trace/ (no-op
        without --trace / --run-dir)."""
        if tracer is None or not run_dir:
            return None
        tdir = os.path.join(run_dir, "trace")
        return tracer.write_chrome_trace(
            os.path.join(tdir, f"spans.p{pid}.trace.json"))

    groups = [args.group] * (args.configs // args.group)
    if args.configs % args.group:
        groups.append(args.configs % args.group)

    # completed groups (journal is append-only and groups run in order,
    # so the finished set is a prefix); the first incomplete group may
    # have an in-flight checkpoint to restore
    done_recs = {}
    if resuming:
        for rec in _read_journal(journal_path):
            if rec.get("event") == "group":
                done_recs[rec["group"]] = rec
    frontier = len(done_recs)

    def ckpt_path(gi):
        # single-process: one .npz file; pod mode: a v4 distributed
        # checkpoint DIRECTORY of per-process shard files (same name —
        # SweepRunner.checkpoint/restore handle either layout)
        return os.path.join(run_dir, f"group_{gi}.ckpt.npz")

    def metrics_path(gi, proc=None):
        # per-process metrics files on a pod (each process journals its
        # own stream; contents are identical modulo timing — process 0's
        # is the canonical copy analysis tools read)
        proc = pid if proc is None else proc
        name = (f"metrics_g{gi}.jsonl" if nproc == 1
                else f"metrics_g{gi}.p{proc}.jsonl")
        return os.path.join(run_dir, name)

    def journal(rec):
        """One journal line — process 0 owns the journal on a pod."""
        if primary:
            _journal_append(journal_path, rec)

    def build_runner(gi, n_cfg):
        param = read_solver_param(args.solver)
        param.failure_pattern.type = "gaussian"
        param.failure_pattern.mean = args.mean
        param.failure_pattern.std = args.std
        param.random_seed = 7 + gi
        param.display = 0
        param.ClearField("test_interval")
        solver = Solver(param, compute_dtype="bfloat16",
                        fault_process=args.process)
        if run_dir:
            # per-group sweep records (one per chunk, per-config loss
            # vectors + quarantine ids); the in-flight group resumes
            # in append mode ONLY when its checkpoint landed — the
            # pre-preemption records then cover exactly the chunks the
            # restored state already replayed (no checkpoint = the
            # group restarts from scratch, so its records must too)
            # unbuffered: a durable run's records are crash evidence —
            # they must be on disk when the scheduler's SIGKILL lands,
            # not sitting in a userspace buffer (one flush per chunk
            # record is noise next to the chunk's device time)
            solver.enable_metrics(JsonlSink(
                metrics_path(gi),
                append=(resuming and gi == frontier
                        and _ckpt_ready(ckpt_path(gi))),
                unbuffered=True))
        # per-group block: groups at or under the block need no
        # blocking (they already fit the activation budget); an
        # indivisible larger remainder falls back to its gcd rather
        # than crashing after earlier groups burned their wall-clock
        if not args.block or n_cfg <= args.block:
            block = 0
        elif n_cfg % args.block == 0:
            block = args.block
        else:
            block = math.gcd(n_cfg, args.block)
        runner = SweepRunner(solver, n_configs=n_cfg, config_block=block,
                             precompile_chunk=args.chunk,
                             pipeline_depth=args.pipeline_depth,
                             stall_timeout_s=args.stall_timeout or None,
                             engine=args.engine,
                             dtype_policy=args.dtype_policy or None,
                             packed_state=args.packed_state)
        if tracer is not None:
            runner.enable_tracing(tracer)
        # engine attribution for sweep_report.json: what actually RAN
        # (the runner resolves fallbacks loudly), never the request.
        # Groups can resolve differently (config_block is computed per
        # group size), so a disagreement reports "mixed" and a stale
        # fallback reason is cleared when no group carries one — the
        # report can never pin a kernel label on a jax run
        engine_info["engine_requested"] = args.engine
        prev = engine_info.get("engine_resolved")
        engine_info["engine_resolved"] = (
            runner.engine_resolved
            if prev in (None, runner.engine_resolved) else "mixed")
        if runner.engine_fallback_reason:
            engine_info["engine_fallback_reason"] = \
                runner.engine_fallback_reason
        elif engine_info["engine_resolved"] == runner.engine_resolved:
            engine_info.pop("engine_fallback_reason", None)
        # the completion contract: every config trains for --iters
        # iterations or fails with a diagnosis after its retry budget;
        # quarantined lanes are reclaimed and re-seeded at chunk
        # boundaries instead of burning compute as frozen masks
        runner.enable_self_healing(budget=args.iters,
                                   max_retries=args.max_retries,
                                   backoff_iters=args.retry_backoff)
        return runner

    # --- the completion-contract ledger (sweep_report.json) ---
    # global config id -> terminal/pending entry; groups contribute
    # their local reports offset by the configs before them
    offsets = [0]
    for n_cfg in groups[:-1]:
        offsets.append(offsets[-1] + n_cfg)
    ledger: dict = {}
    #: engine attribution, filled by the first build_runner (identical
    #: across groups: same solver flags, same mesh)
    engine_info: dict = {}

    def _merge_report(gi, report):
        off = offsets[gi]
        for cs, v in (report.get("completed") or {}).items():
            ledger[off + int(cs)] = dict(v, group=gi)
        for cs, v in (report.get("failed") or {}).items():
            ledger[off + int(cs)] = dict(v, group=gi)
        for cs, v in (report.get("active") or {}).items():
            ledger[off + int(cs)] = dict(v, group=gi, status="pending")
        for e in report.get("pending") or []:
            ledger[off + int(e["config"])] = {
                "status": "pending", "group": gi,
                "attempt": int(e["attempt"])}

    def _write_report(status: str, exit_code: int) -> dict:
        """Assemble (and, for durable runs, write) the sweep completion
        report: every requested config accounted for as completed /
        failed / pending."""
        for c in range(args.configs):
            # configs of groups never started (preempted early) are
            # still accounted for: the contract names every one
            ledger.setdefault(c, {"status": "pending"})
        n_done = sum(1 for v in ledger.values()
                     if v.get("status") == "completed")
        failed = sorted(c for c, v in ledger.items()
                        if v.get("status") == "failed")
        retried = sorted(
            c for c, v in ledger.items()
            if int(v.get("attempts", v.get("attempt", 1)) or 1) > 1)
        report = {
            "schema_version": 1,
            "status": status, "exit_code": exit_code,
            "requested": args.configs,
            "completed": n_done, "failed": failed, "retried": retried,
            "max_retries": args.max_retries,
            "retry_backoff": args.retry_backoff,
            **engine_info,
            "configs": {str(c): ledger[c] for c in sorted(ledger)},
        }
        if run_dir and primary:
            path = os.path.join(run_dir, "sweep_report.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2)
            os.replace(tmp, path)
        return report

    # --- deterministic NaN injection (CI test hook) ---
    inject = None
    if args.inject_nan:
        spec = args.inject_nan
        always = spec.endswith(":always")
        body = spec[:-len(":always")] if always else spec
        cfg_s, it_s = body.split("@")
        inject = {"config": int(cfg_s), "iter": int(it_s),
                  "always": always, "done": False}

    def _maybe_inject(runner, gi):
        """Poison the injected config's lane params with NaN once it is
        resident and the target iteration has been reached (a step
        boundary — deterministic for a fixed poll cadence)."""
        if inject is None or (inject["done"] and not inject["always"]):
            return
        local = inject["config"] - offsets[gi]
        if not (0 <= local < runner.n) or runner.iter < inject["iter"]:
            return
        lane = runner.config_report()["active"].get(local, {}).get("lane")
        if lane is None:
            return
        key = runner.solver._fault_keys[0]
        layer, slot = key.rsplit("/", 1)
        orig = runner.params[layer][int(slot)]

        def _poison(row):
            row = np.array(row)
            row.flat[0] = np.nan
            return row

        # addressable-shard edit: on a pod only the process owning the
        # lane's rows mutates anything; everyone rebuilds the handle
        # from the same (byte-identical elsewhere) buffers
        runner.params[layer][int(slot)] = runner._edit_leaf_rows(
            orig, {int(lane): _poison})
        inject["done"] = True
        print(f"Injected NaN into config {inject['config']} "
              f"(lane {lane}) at iteration {runner.iter}", flush=True)

    # --- preemption handling (durable runs only) ---
    preempt: dict = {}

    def _on_signal(signum, frame):
        preempt.setdefault("signal", signal.Signals(signum).name)
        preempt.setdefault("t", time.monotonic())

    if run_dir:
        os.makedirs(run_dir, exist_ok=True)
        if not resuming and primary:
            with open(manifest_path, "w") as f:
                json.dump({k: getattr(args, k) for k in MANIFEST_ARGS},
                          f, indent=2)
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def _close_runner(runner):
        logger = runner.solver.metrics_logger
        runner.close()
        if logger is not None:
            logger.close()

    def _preempt_exit(runner, gi):
        """Grace path: drain, checkpoint the in-flight group, journal
        the preemption, exit with the distinct 'retry me' code. The
        sweep report is written too (status "preempted") so partial
        progress is inspectable while the run waits for its retry.
        On a pod every process runs this together (the preempt flag was
        agreed via _any_preempt); the checkpoint decision is agreed
        too — the collective v4 capture would deadlock if one process
        thought its grace budget had run out and its peers did not."""
        left = args.grace_seconds - (time.monotonic() - preempt["t"])
        do_ckpt = runner is not None and multihost.process_any(left > 0)
        wrote = None
        if do_ckpt:
            wrote = runner.checkpoint(ckpt_path(gi))
        if runner is not None:
            _merge_report(gi, runner.config_report())
            _close_runner(runner)
        journal({
            "event": "preempt", "signal": preempt["signal"],
            "group": gi,
            "iter": int(runner.iter) if runner is not None else 0,
            "checkpoint": os.path.basename(wrote) if wrote else None})
        # best-effort post-mortem timeline (per-process file only —
        # no merge barrier on the preempt path)
        _write_trace()
        _write_report("preempted", PREEMPTED_EXIT)
        print(f"Preempted by {preempt['signal']} in group {gi}"
              + (f"; checkpoint {wrote}" if wrote
                 else "; grace budget exhausted, no checkpoint"),
              flush=True)
        sys.exit(PREEMPTED_EXIT)

    def _stall_exit(err, runner, gi):
        """A chunk's bookkeeping stalled past --stall-timeout: the
        runner already wrote a best-effort emergency checkpoint; move
        it into the run dir so --resume restores mid-group, journal the
        stall, and exit with the 'retry me' code."""
        wrote = None
        if run_dir and getattr(err, "checkpoint_path", None) \
                and os.path.exists(err.checkpoint_path):
            shutil.move(err.checkpoint_path, ckpt_path(gi))
            wrote = ckpt_path(gi)
        if runner is not None:
            _merge_report(gi, runner.config_report())
        if run_dir:
            journal({
                "event": "stall", "group": gi,
                "iter": int(runner.iter) if runner is not None else 0,
                "checkpoint": os.path.basename(wrote) if wrote else None})
            _write_trace()
            _write_report("preempted", PREEMPTED_EXIT)
            print(f"Stalled in group {gi}: {err}"
                  + (f"; checkpoint {wrote}" if wrote else ""),
                  flush=True)
            # the consumer thread is stuck: skip the close barriers and
            # let the daemon threads die with the process
            sys.exit(PREEMPTED_EXIT)
        raise err

    # checkpoint cadence in iterations, aligned to chunk boundaries so
    # an interrupted-then-resumed run replays the exact same chunks
    ck_every = 0
    if args.checkpoint_every and run_dir:
        ck_every = max(args.chunk, math.ceil(
            args.checkpoint_every / max(args.chunk, 1)) * args.chunk)
    # preemption poll slice: the signal handler only sets a flag, so a
    # durable run must return from step() at sub-group granularity or
    # the grace budget expires before the flag is ever read — even with
    # periodic checkpoints off, poll every few dispatch windows
    poll_every = ck_every or (args.chunk * 4 if run_dir else 0)

    from rram_caffe_simulation_tpu.async_exec import StallError

    t_total = time.perf_counter()
    done = 0
    blocks_used, overlap_s, host_blocked_s = [], [], []
    runner = None
    gi = -1
    # the prefetcher is a context manager: leaving the block (a raised
    # step, a preemption sys.exit) cancels any in-flight build instead
    # of leaking its consumer threads
    with GroupPrefetcher() as prefetch:
        prefetch.tracer = tracer
        for gi, n_cfg in enumerate(groups):
            if gi in done_recs:
                rec = done_recs[gi]
                blocks_used.append(rec.get("config_block", 0))
                overlap_s.append(rec.get("setup_overlap_seconds", 0.0))
                host_blocked_s.append(rec.get("host_blocked_seconds",
                                              0.0))
                rep = rec.get("report")
                if rep:
                    _merge_report(gi, {"completed": rep.get("completed",
                                                            {}),
                                       "failed": rep.get("failed", {})})
                else:
                    # legacy journal (pre-report): the group finished,
                    # so every config counts as completed first-try
                    losses = rec.get("loss") or []
                    _merge_report(gi, {"completed": {
                        str(i): {"status": "completed", "attempts": 1,
                                 "loss": (losses[i] if i < len(losses)
                                          else None)}
                        for i in range(n_cfg)}})
                done += n_cfg
                continue
            if _any_preempt(preempt):
                # signal landed between groups: the journal is already
                # consistent, nothing in flight to checkpoint
                _preempt_exit(None, gi)
            if runner is None:
                restoring = (resuming and gi == frontier
                             and _ckpt_ready(ckpt_path(gi)))
                if restoring:
                    # cross-topology resume (v4 reshards state; the
                    # metrics layout is named by process count): adopt
                    # the previous topology's canonical stream when
                    # ours does not exist yet, so the group's records
                    # stay one coherent file
                    if not os.path.exists(metrics_path(gi)):
                        for cand in (
                                os.path.join(run_dir,
                                             f"metrics_g{gi}.jsonl"),
                                os.path.join(
                                    run_dir,
                                    f"metrics_g{gi}.p0.jsonl")):
                            if os.path.exists(cand):
                                shutil.copyfile(cand, metrics_path(gi))
                                break
                    # records beyond the checkpoint would duplicate
                    # once the restored state re-runs those chunks
                    # (each process truncates its OWN metrics file)
                    _truncate_metrics(metrics_path(gi),
                                      _ckpt_iter(ckpt_path(gi)))
                runner = build_runner(gi, n_cfg)
                if restoring:
                    runner.restore(ckpt_path(gi))
                    print(f"group {gi}: restored in-flight checkpoint "
                          f"at iteration {runner.iter}", flush=True)
            if not args.no_overlap and gi + 1 < len(groups):
                # group B's whole setup (fault draw, placement, dataset,
                # AOT compile) runs behind group A's execution
                prefetch.start(build_runner, gi + 1, groups[gi + 1])
            t0 = time.perf_counter()
            # completion contract: the group ends only when every one
            # of its configs is completed (budget trained, possibly
            # after retries in reclaimed lanes) or failed-with-diagnosis
            try:
                while not runner.healing_complete():
                    _maybe_inject(runner, gi)
                    runner.step(poll_every or args.iters,
                                chunk=args.chunk)
                    if _any_preempt(preempt):
                        _preempt_exit(runner, gi)
                    if ck_every and not runner.healing_complete():
                        runner.checkpoint(ckpt_path(gi))
            except StallError as e:
                _stall_exit(e, runner, gi)
            report = runner.config_report()
            completed, failed = report["completed"], report["failed"]
            if run_dir and any(v.get("loss") is None
                               for v in completed.values()):
                # restored checkpoint already covered every iteration
                # (preempted at the very end of the group): the final
                # per-config losses are the last journaled chunk record
                mrecs = [r for r in _read_journal(metrics_path(gi))
                         if r.get("type") is None]
                for c, v in completed.items():
                    lane = v.get("lane")
                    if v.get("loss") is not None or lane is None:
                        continue
                    # take the LAST record in which this config still
                    # occupied its harvest lane — a lane refilled after
                    # the config completed carries another config's
                    # trajectory in later records
                    for r in reversed(mrecs):
                        lm = r.get("lane_map")
                        if lm is not None and (lane >= len(lm)
                                               or lm[lane] != int(c)):
                            continue
                        lv = r.get("loss")
                        lv = lv if isinstance(lv, list) else [lv]
                        if lane < len(lv):
                            v["loss"] = lv[lane]
                        break
            final_loss = [completed.get(c, {}).get("loss")
                          for c in range(n_cfg)]
            failed_ids = sorted(failed)
            retried = sorted(c for c, v in {**completed,
                                            **failed}.items()
                             if int(v.get("attempts", 1)) > 1)
            broken_vals = [v.get("broken") for v in completed.values()
                           if v.get("broken") is not None]
            broken_mean = (float(np.mean(broken_vals)) if broken_vals
                           else float(runner.broken_fractions().mean()))
            _merge_report(gi, report)
            dt = time.perf_counter() - t0
            blocks_used.append(runner.config_block)
            pipe = runner.setup_record().get("pipeline", {})
            overlap_s.append(round(pipe.get("setup_overlap_seconds",
                                            0.0), 2))
            host_blocked_s.append(round(pipe.get("host_blocked_seconds",
                                                 0.0), 4))
            fault_npz = None
            if run_dir:
                fault_npz = f"group_{gi}_faults.npz"
                runner.save_fault_states(
                    os.path.join(run_dir, fault_npz), background=False)
            _close_runner(runner)
            runner = None
            # NOTE: a signal that landed during finalization is serviced
            # only AFTER the group's journal line below — exiting first
            # would discard a fully trained group on resume
            if run_dir:
                journal({
                    "event": "group", "group": gi, "n_configs": n_cfg,
                    "iters": args.iters,
                    "config_block": blocks_used[-1],
                    "loss": final_loss,
                    "broken_mean": broken_mean,
                    "quarantine": failed_ids,
                    "report": {
                        "completed": {str(c): v
                                      for c, v in completed.items()},
                        "failed": {str(c): v for c, v in failed.items()}},
                    "fault_npz": fault_npz,
                    "wall_seconds": round(dt, 3),
                    "setup_overlap_seconds": overlap_s[-1],
                    "host_blocked_seconds": host_blocked_s[-1],
                    "checkpoint_write_seconds": round(pipe.get(
                        "checkpoint_write_seconds", 0.0), 4)})
                if primary:
                    _ckpt_remove(ckpt_path(gi))  # group done; stale
            done += n_cfg
            tail = ""
            if retried:
                tail += f"; retried {retried}"
            if failed_ids:
                tail += f"; failed {failed_ids}"
            print(f"group {gi}: {n_cfg} configs x {args.iters} iters in "
                  f"{dt / 60:.2f} min (broken mean {broken_mean:.3f})"
                  f"{tail}; {done}/{args.configs} done", flush=True)
            if gi + 1 < len(groups) and (gi + 1) not in done_recs:
                if _any_preempt(preempt):
                    # don't burn grace budget building a group we are
                    # about to abandon (the with-block cancels the
                    # prefetch)
                    _preempt_exit(None, gi + 1)
                runner = (build_runner(gi + 1, groups[gi + 1])
                          if args.no_overlap else prefetch.take())
                if _any_preempt(preempt):
                    _preempt_exit(runner, gi + 1)
    total_min = (time.perf_counter() - t_total) / 60
    if tracer is not None and run_dir:
        # per-process export, then ONE merged Perfetto timeline: the
        # barrier guarantees every process's file is on disk before
        # process 0 folds them (pid = process index, tid = thread role
        # — both processes' dispatcher/consumer threads stay
        # distinguished on the shared wall-clock base)
        _write_trace()
        if nproc > 1:
            multihost.barrier("trace-export")
        if primary:
            tdir = os.path.join(run_dir, "trace")
            # merge THIS topology's files only (range(nproc), not a
            # directory glob): a preempted higher-process-count
            # attempt leaves stale spans.pN files behind, and a glob
            # would fold a phantom process into the merged timeline
            parts = [p for p in
                     (os.path.join(tdir, f"spans.p{i}.trace.json")
                      for i in range(nproc))
                     if os.path.exists(p)]
            from rram_caffe_simulation_tpu.observe.spans import \
                merge_chrome_traces
            merge_chrome_traces(
                parts, os.path.join(tdir, "merged.trace.json"))
    n_failed = sum(1 for v in ledger.values()
                   if v.get("status") == "failed")
    status = "partial" if n_failed else "clean"
    exit_code = PARTIAL_EXIT if n_failed else 0
    sweep_report = _write_report(status, exit_code)
    rec = {
        "configs": args.configs,
        "iters_per_config": args.iters,
        "batch": 100,
        "groups": groups,
        "config_block": blocks_used,
        "wall_minutes_one_chip": round(total_min, 2),
        "configs_per_hour_one_chip": round(args.configs
                                           / (total_min / 60), 1),
        "v4_8_projection_minutes": round(total_min / 8, 2),
        "compute_dtype": "bfloat16",
        "process": args.process,
        "pipeline_depth": args.pipeline_depth,
        "overlapped_groups": not args.no_overlap,
        # per-group async accounting: setup seconds hidden behind the
        # previous group's execution, and the dispatcher's host-blocked
        # seconds across the group's chunk dispatches
        "group_setup_overlap_seconds": overlap_s,
        "host_blocked_seconds": host_blocked_s,
        "run_dir": run_dir or None,
        "groups_resumed": len(done_recs),
        # pod mode: how the config axis was laid out (1 process /
        # N chips is the classic single-host row)
        "processes": nproc,
        "chips": len(jax.devices()),
        # the completion contract's summary (full per-config ledger in
        # <run-dir>/sweep_report.json for durable runs)
        "status": status,
        "completed_configs": sweep_report["completed"],
        "failed_configs": sweep_report["failed"],
        "retried_configs": sweep_report["retried"],
    }
    if run_dir:
        journal({"event": "done", "configs": args.configs,
                 "status": status})
    if primary:
        # one JSON line per RUN, not per process
        print(json.dumps(rec), flush=True)
    if exit_code:
        sys.exit(exit_code)
    return rec


if __name__ == "__main__":
    main()

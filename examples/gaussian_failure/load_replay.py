#!/usr/bin/env python
"""Load-replay harness: make the fleet sweat under the watchtower.

Replays a deterministic, bursty, multi-tenant request stream — an
order of magnitude past the fleet guard's 6 requests — through a REAL
2-worker fleet with the full metrics plane live (per-beat worker
socket scrapes, the ``<fleet>/metrics.prom`` Prometheus rollup, and
the declarative alert rules), then stresses the alert lifecycle with
an induced swap storm and a SIGKILL, and (optionally) drives the
BacklogScaler through a spawn/drain cycle on a second fleet.

Legs, in order:

1. **Dedicated references (the unmonitored run)**: the stream's two
   physics subsets through two dedicated, socket-less, watchtower-less
   `SweepService`s — the ground truth the MONITORED fleet must
   reproduce byte-for-byte (losses + fault npz + config-id
   allocation). Monitoring that perturbs results is worse than no
   monitoring.
2. **Monitored replay**: the same stream, submitted on its bursty
   arrival schedule, through one fleet spool feeding 2 pinned
   subprocess workers while the controller scrapes, evaluates alert
   rules, and rewrites the rollup every beat. Measures sustained
   occupancy, p50/p99 turnaround, and SLO burn.
3. **SIGKILL**: the drift worker dies mid-request — `worker_death`
   fires, the request requeues and completes on the survivor (which
   hot-swaps to drift).
4. **Swap storm**: alternating-pin requests ping-pong the sole
   survivor between its two resident program sets — `swap_storm`
   fires on each command beat and resolves once the storm drains.
5. **Scaler cycle** (``--scaler-leg``): a fresh fleet born with ZERO
   workers and a deep backlog — the controller spawns workers from
   ``worker_cmd`` (scale up), then drains an idle one once the
   projection collapses (scale down).

    python examples/gaussian_failure/load_replay.py \\
        --requests 60 --bench-out BENCH_FLEET_LOAD_r01.json

`scripts/check_fleet_load.py` runs this same harness at guard scale
in CI. Deterministic given ``--seed``: the stream, pins, and burst
schedule all come from one `random.Random`.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import random

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

LANES = 4
CHUNK = 10
PROC_A = "endurance_stuck_at"
PROC_B = "conductance_drift:nu=0.1"
TENANTS = ("alice", "bob", "carol", "dave")
SLO_SECONDS = 30.0
MIN_OCCUPANCY = 0.90


# ---------------------------------------------------------------------------
# the stream

def build_stream(n_requests=60, seed=1701, iters=20):
    """The deterministic bursty multi-tenant stream: a list of request
    dicts (sortable ids = submission order) each carrying an
    ``offset_s`` arrival time. Bursts of 4-8 requests land together
    (multi-tenant, mixed physics) separated by short gaps — the
    arrival pattern that makes the BacklogScaler's projection move.
    Each request carries 3-5 configs so a burst's share per pinned
    worker stays >= the lane count through the burst's drain — the
    occupancy floor is a property of the stream, not of luck."""
    rng = random.Random(seed)
    out, t = [], 0.0
    i = 0
    while i < n_requests:
        burst = min(rng.randint(4, 8), n_requests - i)
        for _ in range(burst):
            tenant = rng.choice(TENANTS)
            proc = PROC_A if rng.random() < 0.5 else PROC_B
            configs = [{"mean": rng.randint(430, 530),
                        "std": rng.randint(80, 110)}
                       for _ in range(rng.randint(3, 5))]
            out.append({"id": f"m{i:04d}-{tenant}", "tenant": tenant,
                        "process": proc, "iters": iters,
                        "configs": configs,
                        "offset_s": round(t + rng.random() * 0.2, 3)})
            i += 1
        t += rng.uniform(1.0, 2.5)
    return out


def build_storm(n=6, iters=10):
    """The adversarial swap-storm mix: single-config requests strictly
    alternating the two physics. Against a one-worker fleet every
    request forces a hot swap — after the first build both program
    sets are resident, so the storm is a resident-reactivation
    ping-pong (the cheap kind of sweat)."""
    out = []
    for i in range(n):
        proc = PROC_B if i % 2 == 0 else PROC_A
        out.append({"id": f"s{i:02d}-storm", "tenant": "storm",
                    "process": proc, "iters": iters,
                    "configs": [{"mean": 500 - 5 * i, "std": 100}]})
    return out


def watchtower_rules():
    """The default rule set re-tuned for guard timescales: a swap
    command lands on ONE beat (the next command is seconds of rebuild
    away), so `swap_storm` trips per command beat instead of requiring
    three consecutive ones."""
    from rram_caffe_simulation_tpu.serve.fleet.alerts import (
        DEFAULT_RULES, AlertRule)
    rules = []
    for spec in DEFAULT_RULES:
        spec = dict(spec)
        if spec["name"] == "swap_storm":
            spec["for_beats"] = 1
            spec["clear_beats"] = 8
        rules.append(AlertRule.from_dict(spec))
    return rules


# ---------------------------------------------------------------------------
# fixtures (same tiny LMDB + net as scripts/check_fleet.py)

def build_db(path):
    import numpy as np
    from rram_caffe_simulation_tpu.data import lmdb_py
    from rram_caffe_simulation_tpu.data.db import array_to_datum
    rng = np.random.RandomState(0)
    with lmdb_py.BulkWriter(path) as w:
        for i in range(24):
            img = rng.randint(0, 255, (1, 8, 8), dtype=np.uint8)
            w.put(b"%08d" % i,
                  array_to_datum(img, int(img.mean() // 64))
                  .SerializeToString())


def write_solver(path, db):
    with open(path, "w") as f:
        f.write(f"""
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
type: "SGD"
max_iter: 1000
display: 0
random_seed: 3
snapshot_prefix: "{os.path.dirname(path)}/snap"
failure_pattern {{ type: "gaussian" mean: 500 std: 100 }}
net_param {{
  name: "loadreplay"
  layer {{ name: "data" type: "Data" top: "data" top: "label"
    data_param {{ source: "{db}" batch_size: 8 }}
    transform_param {{ scale: 0.00390625 }} }}
  layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
    inner_product_param {{ num_output: 4
      weight_filler {{ type: "xavier" }} }} }}
  layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip"
    bottom: "label" top: "loss" }}
}}
""")


def _clean(entry):
    return {k: v for k, v in entry.items() if k != "offset_s"}


def run_dedicated(solver, service_dir, proc, entries):
    """The unmonitored reference: one dedicated service (no socket, no
    controller, no watchtower) fed `entries` in submission order."""
    from rram_caffe_simulation_tpu.serve import Spool, SweepService
    svc = SweepService(solver, service_dir, lanes=LANES, chunk=CHUNK,
                       default_iters=CHUNK, max_retries=1,
                       socket_path=None, save_fault_results=True,
                       poll_interval_s=0.05,
                       fault_process=(None if proc == PROC_A
                                      else proc))
    for e in entries:
        svc.spool.submit(_clean(e))
    code = svc.serve(drain_when_idle=True)
    svc.close()
    if code != 0:
        raise RuntimeError(f"dedicated {proc} service exited {code}")
    spool = Spool(os.path.join(service_dir, "spool"))
    return {e["id"]: spool.read(e["id"]) for e in entries}, service_dir


def _npz_bytes(root, fname):
    import numpy as np
    with np.load(os.path.join(root, "requests", fname)) as z:
        return {k: z[k].tobytes() for k in z.files}


def compare_results(stream, fleet_spool, worker_dirs, worker_spools,
                    dedicated):
    """Monitored fleet vs unmonitored references: list of mismatch
    strings (empty = byte-identical)."""
    import numpy as np
    bad = []
    for e in stream:
        rid, proc = e["id"], e["process"]
        ded_req, ded_root = dedicated[proc]
        ref = ded_req[rid]
        got = fleet_spool.read(rid)
        if got is None or got.get("state") != "done":
            bad.append(f"{rid}: not terminal "
                       f"({got and got.get('state')})")
            continue
        if got.get("status") != "completed":
            bad.append(f"{rid}: ended {got.get('status')!r} "
                       f"({got.get('reason')!r})")
            continue
        wid = got.get("worker")
        wreq = worker_spools[wid].read(rid)
        if wreq.get("cfg_ids") != ref.get("cfg_ids"):
            bad.append(f"{rid}: cfg ids {wreq.get('cfg_ids')} on "
                       f"{wid} != dedicated {ref.get('cfg_ids')}")
            continue
        for cfg, v in got.get("results", {}).items():
            rv = ref["results"][cfg]
            if np.float64(v["loss"]).tobytes() \
                    != np.float64(rv["loss"]).tobytes():
                bad.append(f"{rid}/{cfg}: loss {v['loss']!r} != "
                           f"dedicated {rv['loss']!r}")
            elif _npz_bytes(worker_dirs[wid], v["fault_npz"]) \
                    != _npz_bytes(ded_root, rv["fault_npz"]):
                bad.append(f"{rid}/{cfg}: fault npz differs")
    return bad


def measure_occupancy(worker_dirs, lanes):
    """Merged steady-state lane occupancy across the fleet.

    check_serve_contract/check_fleet exclude the run TAIL — records
    where "remaining work cannot fill the pool" — using the stream's
    FINAL config total, which is exact for their all-at-once
    submission. Under bursty arrivals that rule under-counts: a chunk
    that ran while a burst drained and the next burst had not ARRIVED
    yet would be charged against occupancy for work that did not
    exist. The faithful generalization scans metrics.jsonl in append
    order and excludes records where (configs admitted SO FAR - done)
    < lanes — the same "pool cannot be filled" criterion, evaluated
    against what had actually arrived. Returns
    (steady_mean, steady_n, duty_mean, all_n): `steady_mean` is the
    guarded metric; `duty_mean` is the unexcluded all-records mean
    (the burst-gap duty cycle), reported for honesty."""
    occ, duty = [], []
    for root in worker_dirs.values():
        done_iters = []
        rows = []                        # (chunk rec, admitted so far)
        admitted = 0
        path = os.path.join(root, "metrics.jsonl")
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "request":
                    if rec.get("event") == "config_done":
                        done_iters.append(rec["iter"])
                    elif rec.get("event") == "admitted":
                        admitted += rec.get("configs", 0)
                elif rec.get("type") is None \
                        and isinstance(rec.get("lane_map"), list):
                    rows.append((rec, admitted))
        for rec, adm in rows:
            lm = rec["lane_map"]
            frac = sum(1 for c in lm if c >= 0) / len(lm)
            duty.append(frac)
            done = sum(1 for it in done_iters if it <= rec["iter"])
            if adm - done < lanes:
                continue
            occ.append(frac)
    if not duty:
        return 0.0, 0, 0.0, 0
    steady = (sum(occ) / len(occ), len(occ)) if occ else (0.0, 0)
    return steady[0], steady[1], sum(duty) / len(duty), len(duty)


def alert_events(fleet_jsonl):
    """alert name -> {"firing": n, "resolved": n} from fleet.jsonl."""
    out = {}
    if not os.path.exists(fleet_jsonl):
        return out
    with open(fleet_jsonl) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") != "alert":
                continue
            slot = out.setdefault(rec["alert"],
                                  {"firing": 0, "resolved": 0})
            if rec.get("event") in slot:
                slot[rec["event"]] += 1
    return out


# ---------------------------------------------------------------------------
# the harness

def _beat_until(ctl, cond, deadline_s, sleep_s=0.1, what="condition"):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        ctl.beat()
        if cond():
            return
        time.sleep(sleep_s)
    raise RuntimeError(f"load replay: {what} not reached within "
                       f"{deadline_s:g} s")


def run(workdir, n_requests=60, iters=20, seed=1701, storm_n=6,
        scaler_leg=True, verbose=True):
    """The full replay. Returns the measurement summary dict; raises
    RuntimeError when the fleet cannot be driven through the legs."""
    from rram_caffe_simulation_tpu import cache as perf_cache
    from rram_caffe_simulation_tpu.observe.metrics_registry import (
        parse_exposition, validate_rollup)
    from rram_caffe_simulation_tpu.serve import Spool
    from rram_caffe_simulation_tpu.serve.fleet import WorkerTable
    from rram_caffe_simulation_tpu.serve.fleet.controller import \
        FleetController

    def say(msg):
        if verbose:
            print(msg, flush=True)

    os.makedirs(workdir, exist_ok=True)
    cache_dir = os.path.join(workdir, "cache")
    perf_cache.enable_compilation_cache(cache_dir,
                                        min_compile_time_s=0.05)
    os.environ["RRAM_TPU_CACHE_DIR"] = cache_dir
    db = os.path.join(workdir, "db")
    solver = os.path.join(workdir, "solver.prototxt")
    build_db(db)
    write_solver(solver, db)

    stream = build_stream(n_requests, seed=seed, iters=iters)
    total_cfgs = sum(len(e["configs"]) for e in stream)

    say(f"=== leg 1: dedicated (unmonitored) references — "
        f"{len(stream)} requests, {total_cfgs} configs ===")
    t_ded = time.perf_counter()
    a_entries = [e for e in stream if e["process"] == PROC_A]
    b_entries = [e for e in stream if e["process"] == PROC_B]
    ded_a, root_a = run_dedicated(
        solver, os.path.join(workdir, "ded_a"), PROC_A, a_entries)
    ded_b, root_b = run_dedicated(
        solver, os.path.join(workdir, "ded_b"), PROC_B, b_entries)
    dedicated = {PROC_A: (ded_a, root_a), PROC_B: (ded_b, root_b)}
    ded_wall = time.perf_counter() - t_ded
    say(f"dedicated references done in {ded_wall:.1f} s "
        f"({len(a_entries)} endurance / {len(b_entries)} drift)")

    say("=== leg 2: monitored replay — bursty arrivals over 2 pinned "
        "workers, watchtower live ===")
    fleet = os.path.join(workdir, "fleet")
    os.makedirs(fleet, exist_ok=True)
    fleet_spool = Spool(os.path.join(fleet, "spool"))
    table = WorkerTable(fleet)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_cmd = [sys.executable, "-m",
                "rram_caffe_simulation_tpu.serve.fleet.worker",
                "--fleet-dir", fleet, "--solver", solver,
                "--lanes", str(LANES), "--chunk", str(CHUNK),
                "--default-iters", str(CHUNK),
                "--poll-interval", "0.05", "--save-fault-results",
                "--slo-seconds", str(SLO_SECONDS),
                "--cache-dir", cache_dir]
    logdir = os.path.join(fleet, "logs")
    os.makedirs(logdir, exist_ok=True)
    procs = {}
    for name, extra in (("w0", []),
                        ("w1", ["--fault-process", PROC_B])):
        log = open(os.path.join(logdir, f"{name}.log"), "wb")
        procs[name] = subprocess.Popen(
            base_cmd + ["--name", name] + extra, env=env, cwd=_REPO,
            stdout=log, stderr=subprocess.STDOUT)
        log.close()
    ctl = FleetController(fleet, heartbeat_timeout_s=30,
                          poll_interval_s=0.0,
                          alert_rules=watchtower_rules())
    worker_dirs = {w: table.worker_dir(w) for w in ("w0", "w1")}
    worker_spools = {w: Spool(os.path.join(d, "spool"))
                     for w, d in worker_dirs.items()}
    summary = {}
    try:
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if set(table.ids()) >= {"w0", "w1"}:
                break
            time.sleep(0.5)
        else:
            raise RuntimeError("workers never registered")
        say("both workers registered; replaying the arrival schedule")

        t_fleet = time.perf_counter()
        t0 = time.monotonic()
        idx, done = 0, set()
        deadline = time.monotonic() + 1800
        while time.monotonic() < deadline:
            now = time.monotonic() - t0
            while idx < len(stream) \
                    and stream[idx]["offset_s"] <= now:
                fleet_spool.submit(_clean(stream[idx]))
                idx += 1
            ctl.beat()
            for e in stream:
                if e["id"] not in done \
                        and fleet_spool.state_of(e["id"]) == "done":
                    done.add(e["id"])
            if idx == len(stream) and len(done) == len(stream):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError(
                f"monitored replay incomplete: {len(done)}/"
                f"{len(stream)} terminal inside 1800 s")
        fleet_wall = time.perf_counter() - t_fleet
        say(f"monitored replay: {len(stream)} requests terminal in "
            f"{fleet_wall:.1f} s")

        mismatches = compare_results(stream, fleet_spool, worker_dirs,
                                     worker_spools, dedicated)
        occupancy, occ_n, duty, duty_n = measure_occupancy(
            worker_dirs, LANES)
        say(f"byte-identity: {len(mismatches)} mismatch(es); "
            f"occupancy {occupancy:.1%} over {occ_n} steady-state "
            f"records (duty {duty:.1%} over all {duty_n})")

        rollup_path = os.path.join(fleet, "metrics.prom")
        rollup_text = open(rollup_path, encoding="utf-8").read()
        rollup_violations = validate_rollup(rollup_text)
        samples = parse_exposition(rollup_text)

        def q(quant):
            return samples.get(("rram_fleet_turnaround_seconds",
                                (("quantile", quant),)), 0.0)

        summary.update({
            "requests_main": len(stream),
            "configs_main": total_cfgs,
            "identity_mismatches": mismatches,
            "occupancy": round(occupancy, 4),
            "occupancy_records": occ_n,
            "lane_duty_ratio": round(duty, 4),
            "lane_duty_records": duty_n,
            "p50_s": round(q("0.5"), 2),
            "p90_s": round(q("0.9"), 2),
            "p99_s": round(q("0.99"), 2),
            "slo_burn_rate": round(
                samples.get(("rram_fleet_slo_burn_rate", ()), 0.0), 3),
            "fleet_wall_s": round(fleet_wall, 2),
            "ded_wall_s": round(ded_wall, 2),
            "rollup_violations": rollup_violations,
            "rollup_path": rollup_path,
        })

        say("=== leg 3: SIGKILL the drift worker mid-request ===")
        kill_entry = {"id": "x0-kill", "tenant": "alice",
                      "process": PROC_B, "iters": 10 * iters,
                      "configs": [{"mean": 500, "std": 100},
                                  {"mean": 480, "std": 100}]}
        fleet_spool.submit(kill_entry)
        started = os.path.join(worker_dirs["w1"], "requests",
                               "x0-kill.jsonl")
        victim_pid = int(table.read("w1")["pid"])
        _beat_until(ctl, lambda: os.path.exists(started)
                    and "started" in open(started).read(),
                    600, what="kill request start")
        os.kill(victim_pid, signal.SIGKILL)
        procs["w1"].wait()
        say(f"SIGKILLed w1 (pid {victim_pid})")
        _beat_until(ctl,
                    lambda: fleet_spool.state_of("x0-kill") == "done",
                    600, sleep_s=0.2, what="killed-request completion")
        final = fleet_spool.read("x0-kill")
        if final.get("status") != "completed":
            raise RuntimeError(f"kill request ended "
                               f"{final.get('status')!r}")
        say(f"killed request completed on {final.get('worker')} "
            "(requeue + hot swap)")

        say(f"=== leg 4: swap storm — {storm_n} alternating-pin "
            "requests against the sole survivor ===")
        storm = build_storm(storm_n, iters=max(iters // 2, 10))
        for e in storm:
            fleet_spool.submit(_clean(e))
        _beat_until(ctl,
                    lambda: all(fleet_spool.state_of(e["id"]) == "done"
                                for e in storm),
                    900, sleep_s=0.2, what="storm drain")
        storm_status = {e["id"]: fleet_spool.read(e["id"]).get("status")
                        for e in storm}
        if set(storm_status.values()) != {"completed"}:
            raise RuntimeError(f"storm requests not all completed: "
                               f"{storm_status}")
        # idle beats so the beat-counted hysteresis can resolve what
        # the storm fired
        for _ in range(15):
            ctl.beat()
            time.sleep(0.05)
        alerts = alert_events(os.path.join(fleet, "fleet.jsonl"))
        say(f"alert lifecycle: { {k: dict(v) for k, v in alerts.items()} }")

        summary.update({
            "requests_total": len(stream) + 1 + len(storm),
            "configs_total": total_cfgs + 2
            + sum(len(e["configs"]) for e in storm),
            "storm_requests": len(storm),
            "kill_completed_on": final.get("worker"),
            "alerts": alerts,
        })

        # clean drain of the survivor
        with open(os.path.join(worker_dirs["w0"], "DRAIN"), "w"):
            pass
        procs["w0"].wait(timeout=120)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()

    if scaler_leg:
        say("=== leg 5: scaler cycle — zero workers, deep backlog, "
            "spawn up then drain down ===")
        summary["scale"] = run_scaler_leg(workdir, solver, cache_dir,
                                          verbose=verbose)
    return summary


def run_scaler_leg(workdir, solver, cache_dir, verbose=True):
    """A fresh fleet born empty: the controller must spawn workers
    from `worker_cmd` to absorb the backlog (scale up) and drain an
    idle one once the projection collapses (scale down)."""
    from rram_caffe_simulation_tpu.serve import Spool
    from rram_caffe_simulation_tpu.serve.fleet import BacklogScaler
    from rram_caffe_simulation_tpu.serve.fleet.controller import \
        FleetController

    fleet = os.path.join(workdir, "fleet_scale")
    os.makedirs(fleet, exist_ok=True)
    worker_cmd = (
        f"{sys.executable} -m "
        "rram_caffe_simulation_tpu.serve.fleet.worker "
        "--fleet-dir {fleet} --name {name} "
        f"--solver {solver} --lanes 2 --chunk {CHUNK} "
        f"--default-iters {CHUNK} --poll-interval 0.05 "
        f"--cache-dir {cache_dir}")
    # min_workers=0 makes the down half of the cycle rate-independent:
    # the bootstrap spawn (backlog with zero workers) is the UP, and
    # once the backlog drains the idle worker is over the floor and
    # gets drained — the cycle completes whatever the measured rate
    # projects against the target
    scaler = BacklogScaler(target_seconds=2.0, min_workers=0,
                           max_workers=2, up_after=2, down_after=3,
                           down_factor=0.5)
    ctl = FleetController(fleet, heartbeat_timeout_s=60,
                          poll_interval_s=0.0, default_iters=40,
                          scaler=scaler, worker_cmd=worker_cmd,
                          alert_rules=watchtower_rules())
    spool = Spool(os.path.join(fleet, "spool"))
    entries = [{"id": f"b{i:02d}-scale", "tenant": "batch",
                "process": PROC_A, "iters": 40,
                "configs": [{"mean": 500 - i, "std": 100}
                            for _ in range(3)]}
               for i in range(8)]
    for e in entries:
        spool.submit(e)
    try:
        def cycled():
            state = json.load(open(os.path.join(fleet, "state.json")))
            wt = state.get("watchtower") or {}
            return (all(spool.state_of(e["id"]) == "done"
                        for e in entries)
                    and wt.get("scale_ups", 0) >= 1
                    and wt.get("scale_downs", 0) >= 1)

        _beat_until(ctl, cycled, 900, sleep_s=0.1,
                    what="scaler up/down cycle")
        with open(os.path.join(fleet, "DRAIN"), "w"):
            pass
        code = ctl._drain(timeout_s=180)
        if code != 0:
            raise RuntimeError(f"scaler-leg fleet drain exited {code}")
    finally:
        for p in ctl._spawned.values():
            if p.poll() is None:
                p.kill()
    state = json.load(open(os.path.join(fleet, "state.json")))
    wt = state.get("watchtower") or {}
    result = {"ups": int(wt.get("scale_ups", 0)),
              "downs": int(wt.get("scale_downs", 0))}
    if verbose:
        print(f"scaler cycle: {result['ups']} up / "
              f"{result['downs']} down", flush=True)
    return result


# ---------------------------------------------------------------------------
# CLI

def bench_row(summary):
    alerts = summary.get("alerts") or {}
    scale = summary.get("scale") or {}
    return {
        "bench": "fleet_load_replay",
        "workers": 2,
        "lanes_per_worker": LANES,
        "requests": summary.get("requests_total", 0),
        "configs": summary.get("configs_total", 0),
        "occupancy": summary.get("occupancy", 0.0),
        "lane_duty_ratio": summary.get("lane_duty_ratio", 0.0),
        "p50_turnaround_seconds": summary.get("p50_s", 0.0),
        "p99_turnaround_seconds": summary.get("p99_s", 0.0),
        "slo_burn_rate": summary.get("slo_burn_rate", 0.0),
        "alerts_fired": sum(v["firing"] for v in alerts.values()),
        "alerts_resolved": sum(v["resolved"] for v in alerts.values()),
        "storm_requests": summary.get("storm_requests", 0),
        "scale_ups": scale.get("ups", 0),
        "scale_downs": scale.get("downs", 0),
        "fleet_wall_seconds": summary.get("fleet_wall_s", 0.0),
        "configs_per_hour_aggregate": round(
            summary.get("configs_main", 0) * 3600.0
            / max(summary.get("fleet_wall_s", 1.0), 1e-9), 1),
        "byte_identical": not summary.get("identity_mismatches"),
        "note": "bursty multi-tenant load replay under the live "
                "watchtower (per-beat scrapes + rollup + alert "
                "rules): monitored fleet byte-identical to the "
                "unmonitored dedicated references; SIGKILL + swap "
                "storm alert lifecycle; scaler spawn/drain cycle; "
                "occupancy is steady-state (pool-fillable records), "
                "lane_duty_ratio the unexcluded burst-gap duty "
                "cycle; CPU-measured at guard scale",
    }


def main(argv=None):
    import argparse
    import tempfile
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=60,
                    help="main-phase stream size (storm + kill ride "
                         "on top)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--seed", type=int, default=1701)
    ap.add_argument("--storm", type=int, default=6,
                    help="swap-storm request count")
    ap.add_argument("--workdir", default=None,
                    help="working root (default: a fresh tempdir)")
    ap.add_argument("--no-scaler-leg", action="store_true")
    ap.add_argument("--bench-out", default=None,
                    help="write the BENCH_FLEET_LOAD row here")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_load_")
    summary = run(workdir, n_requests=args.requests, iters=args.iters,
                  seed=args.seed, storm_n=args.storm,
                  scaler_leg=not args.no_scaler_leg)

    ok = True
    if summary["identity_mismatches"]:
        ok = False
        print(f"FAIL: {len(summary['identity_mismatches'])} "
              "byte-identity mismatch(es) under monitoring:")
        for m in summary["identity_mismatches"][:10]:
            print(f"  - {m}")
    if summary["rollup_violations"]:
        ok = False
        print(f"FAIL: rollup exposition violations: "
              f"{summary['rollup_violations']}")
    if summary["occupancy"] < MIN_OCCUPANCY:
        ok = False
        print(f"FAIL: sustained occupancy {summary['occupancy']:.1%} "
              f"< {MIN_OCCUPANCY:.0%}")
    resolved = [a for a, v in (summary.get("alerts") or {}).items()
                if v["firing"] and v["resolved"]]
    if not resolved:
        ok = False
        print("FAIL: no alert completed a firing->resolved lifecycle")

    print(json.dumps(summary, indent=2, default=str))
    if ok and args.bench_out:
        row = bench_row(summary)
        with open(args.bench_out, "w") as f:
            json.dump(row, f, indent=2)
            f.write("\n")
        print(f"bench row written to {args.bench_out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

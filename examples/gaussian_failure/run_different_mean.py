#!/usr/bin/env python
"""Mean-lifetime grid sweep — replaces the reference's
run_different_mean.sh (which fanned 3 configs across 3 GPUs as separate
processes): here one invocation trains every config simultaneously on the
vmapped fault axis of a single TPU.

    python run_different_mean.py 1e8 2e8 4e8 [--std 3e7] [--max-iter N]
"""
import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("means", nargs="+", type=float)
    p.add_argument("--std", type=float, default=3e7)
    p.add_argument("--max-iter", type=int, default=0)
    p.add_argument("--tag", default="")
    p.add_argument("--compute-dtype", default="",
                   help="e.g. bfloat16 (~1.6x; f32 fault dynamics)")
    args = p.parse_args(argv)

    from run_gaussian_exp import main as run
    run_args = [str(args.means[0]), str(args.std), "0", "-y",
                "--tag", args.tag or "_meansweep",
                "--sweep-means", ",".join(str(m) for m in args.means)]
    if args.max_iter:
        run_args += ["--max-iter", str(args.max_iter)]
    if args.compute_dtype:
        run_args += ["--compute-dtype", args.compute_dtype]
    return run(run_args)


if __name__ == "__main__":
    sys.path.insert(0, HERE)
    sys.exit(main())

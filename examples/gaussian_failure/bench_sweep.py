"""Sweep-scaling measurement: CIFAR-10-quick RRAM fault sweep throughput
vs n_configs on the available chips (BASELINE north star: 1000-config
5k-iter sweep < 10 min on a v4-8).

Measures steady-state vmapped-step wall time at the reference operating
point (batch 100, lifetimes ~ N(mean, std)) for a ladder of config counts,
prints configs/hour for the 5k-iter contract, and the projection to 8
chips (the config axis is embarrassingly parallel: zero cross-config
collectives, so 8 chips run 8x the configs at the same step time, minus
the measured data-sharding overhead).

    python examples/gaussian_failure/bench_sweep.py [--iters 60] [--configs 16,64,128]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=60)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--configs", default="16,64,128")
    p.add_argument("--chunk", type=int, default=10,
                   help="iterations scanned per device dispatch")
    p.add_argument("--mean", type=float, default=1e8)
    p.add_argument("--std", type=float, default=3e7)
    p.add_argument("--contract-iters", type=int, default=5000,
                   help="iters per config in the sweep contract")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="async dispatch pipeline depth (SweepRunner "
                        "pipeline_depth); 0 = synchronous per-chunk "
                        "bookkeeping")
    p.add_argument("--engine", default="jax",
                   choices=("jax", "pallas", "auto"),
                   help="crossbar engine request (ENGINE MATRIX); the "
                        "row records engine_resolved — what actually "
                        "ran after any loud fallback")
    p.add_argument("--dtype-policy", default="",
                   help="'' | ternary | int8 quantized sweep compute "
                        "(what arms the pallas kernel at sigma == 0)")
    p.add_argument("--packed", action="store_true",
                   help="bit-packed fault banks (fault/packed.py)")
    p.add_argument("--mesh", default="",
                   help="mesh spec, e.g. 'config=4': shard the config "
                        "axis; the pallas engine runs shard_map'd "
                        "under it (ISSUE 13)")
    p.add_argument("--no-trace", action="store_true",
                   help="skip the span tracer (observe/spans.py) — "
                        "drops the row's phase_breakdown attribution")
    args = p.parse_args(argv)
    # a trailing partial chunk would jit-compile inside the timed window
    args.iters = max(args.iters // args.chunk, 1) * args.chunk

    os.chdir(REPO)
    import jax
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.parallel import SweepRunner
    from rram_caffe_simulation_tpu.utils.io import read_solver_param

    results = []
    for n_cfg in [int(c) for c in args.configs.split(",")]:
        param = read_solver_param(
            "models/cifar10_quick/cifar10_quick_lmdb_solver.prototxt")
        param.failure_pattern.type = "gaussian"
        param.failure_pattern.mean = args.mean
        param.failure_pattern.std = args.std
        param.random_seed = 7
        param.display = 0
        solver = Solver(param)
        mesh = None
        if args.mesh:
            from rram_caffe_simulation_tpu.parallel import mesh_from_spec
            mesh = mesh_from_spec(args.mesh)
        runner = SweepRunner(
            solver, n_configs=n_cfg,
            # same default as bench.py so the two benches measure the
            # same arithmetic under an identical environment
            compute_dtype=os.environ.get("BENCH_DTYPE", "bfloat16")
            or None,
            pipeline_depth=args.pipeline_depth,
            engine=args.engine, dtype_policy=args.dtype_policy or None,
            packed_state=args.packed, mesh=mesh)
        runner.step(max(args.warmup, args.chunk), chunk=args.chunk)
        jax.block_until_ready(runner.params)
        # armed after warmup: the phase breakdown attributes the timed
        # window only (observe/spans.py)
        tracer = None if args.no_trace else runner.enable_tracing()
        t0 = time.perf_counter()
        loss, _ = runner.step(args.iters, chunk=args.chunk)
        jax.block_until_ready(runner.params)
        dt = time.perf_counter() - t0
        steps_per_s = args.iters / dt
        cfg_hours = n_cfg * steps_per_s * 3600 / args.contract_iters
        img_s = n_cfg * steps_per_s * 100
        pipe = runner.setup_record().get("pipeline", {})
        n_chips = len(np.asarray(runner.mesh.devices).ravel())
        phase_extra = {}
        if tracer is not None:
            # span-derived host attribution for the timed window
            # (dispatch / host-blocked / checkpoint / prefetch — the
            # r08+ rows carry the split, not just totals; bucket
            # definitions live in observe/spans.py)
            from rram_caffe_simulation_tpu.observe import \
                spans as obs_spans
            phase_extra = {"phase_breakdown":
                           obs_spans.bench_phase_breakdown(
                               tracer.events())}
        runner.close()
        results.append({
            "n_configs": n_cfg, "steps_per_s": round(steps_per_s, 2),
            "img_per_s_per_chip": round(img_s / n_chips),
            # what actually RAN (the runner resolves engine fallbacks
            # loudly, ISSUE 13) — a mesh row can never claim a kernel
            # that fell back to pure JAX
            "engine_requested": args.engine,
            "engine_resolved": runner.engine_resolved,
            **({"engine_fallback_reason": runner.engine_fallback_reason}
               if runner.engine_fallback_reason else {}),
            "fused_epilogue": runner.fused_epilogue_resolved,
            "chips": n_chips,
            # cfg_hours is the WHOLE runner's rate; per-chip figures
            # divide by the mesh size so a --mesh row cannot inflate
            # the single-chip contract (the 8-chip projection below
            # multiplies the per-chip rate back up)
            "configs_per_hour_aggregate": round(cfg_hours, 1),
            "configs_per_hour_per_chip": round(cfg_hours / n_chips, 1),
            "minutes_for_1000_configs_1chip":
                round(1000 / (cfg_hours / n_chips) * 60, 1),
            "loss_finite": bool(np.isfinite(loss).all()),
            # dispatcher host-blocked seconds across all dispatched
            # chunks (observe `setup` record pipeline shape)
            "pipeline_depth": args.pipeline_depth,
            "host_blocked_seconds":
                round(pipe.get("host_blocked_seconds", 0.0), 4),
            **phase_extra,
        })
        print(json.dumps(results[-1]))

    best = max(results, key=lambda r: r["configs_per_hour_per_chip"])
    proj = {
        "projection": "v4-8 (8 chips, config axis sharded)",
        "basis_n_configs_per_chip": best["n_configs"],
        "minutes_for_1000_configs_8chips":
            round(1000 / (8 * best["configs_per_hour_per_chip"]) * 60, 1),
        "target_minutes": 10,
    }
    proj["meets_target"] = (
        proj["minutes_for_1000_configs_8chips"] < proj["target_minutes"])
    print(json.dumps(proj))
    return results, proj


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Magnitude-prune FC layers of a pretrained model and emit the neuron
ordering file consumed by the remapping strategy — parity with the
reference's gaussian_failure/prune_order.py (same CLI, same output format:
one line of space-separated neuron indices per hidden FC group, ascending
by zero-weight count after pruning).
"""
import argparse
import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, ROOT)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("proto")
    p.add_argument("model")
    p.add_argument("prune_ratio", type=float)
    p.add_argument("output_file")
    args = p.parse_args(argv)
    print(f"proto: {args.proto}; model: {args.model}; "
          f"prune_ratio: {args.prune_ratio}; "
          f"output_file: {args.output_file}")

    from rram_caffe_simulation_tpu import api as caffe

    net = caffe.Net(args.proto, args.model, caffe.TEST)
    fc_weights = []
    for key, value in net.params.items():
        # the reference selects layers by "fc" name prefix
        # (prune_order.py:33); we use the fault-target flag, which matches
        # InnerProduct layers regardless of naming
        layer = net.layer_dict[key]
        if getattr(layer, "fault_target", False):
            weights = value[0].data
            flat = weights.flatten()
            rank = np.argsort(np.abs(flat))
            flat[rank[:int(rank.size * args.prune_ratio)]] = 0
            np.copyto(weights, flat.reshape(weights.shape))
            fc_weights.append(weights)

    with open(args.output_file, "w") as wf:
        for i in range(1, len(fc_weights)):
            zero_nums = ((fc_weights[i - 1] == 0).sum(axis=1) +
                         (fc_weights[i] == 0).sum(axis=0))
            indexes = np.argsort(zero_nums)
            wf.write(" ".join(str(x) for x in indexes))
            wf.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())

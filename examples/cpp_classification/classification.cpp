// C++ deployment example (reference examples/cpp_classification/
// classification.cpp parity): a native host program that loads a deploy
// net + weights and classifies one image, printing the top-5
// (confidence, label) pairs in the reference's output format.
//
// The reference links libcaffe and runs the C++ Net directly; here the
// native host embeds the framework through the CPython API — the same
// pattern a C++ serving process uses to drive the TPU runtime (JAX/XLA
// owns the device; C++ owns the process, I/O, and the results). The
// image decode/preprocess/forward all run in the embedded interpreter;
// the predictions cross back over the C API as plain C doubles/strings.
//
// Build and run (see run_cpp_classification.sh):
//   g++ -O2 classification.cpp -o classification \
//       $(python3-config --includes) $(python3-config --embed --ldflags)
//   ./classification deploy.prototxt net.caffemodel mean.binaryproto \
//       labels.txt img.jpg
#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>

static const char* kClassifySource = R"PY(
import os
import sys

sys.path.insert(0, os.environ.get("RRAM_TPU_ROOT", "."))
if os.environ.get("CLASSIFY_PLATFORM"):
    os.environ["JAX_PLATFORMS"] = os.environ["CLASSIFY_PLATFORM"]
    import jax
    jax.config.update("jax_platforms", os.environ["CLASSIFY_PLATFORM"])

import numpy as np


def classify(model_file, trained_file, mean_file, label_file, image_file):
    """Top-5 [(confidence, label)] of one image, reference
    classification.cpp semantics: BGR net, raw scale 255, per-channel
    mean from the binaryproto (SetMean averages it to a channel color),
    single center-crop forward."""
    from rram_caffe_simulation_tpu import api
    from rram_caffe_simulation_tpu.proto import pb

    blob = pb.BlobProto()
    with open(mean_file, "rb") as f:
        blob.ParseFromString(f.read())
    mean_arr = api.io.blobproto_to_array(blob)
    mean_arr = mean_arr.reshape(mean_arr.shape[-3:])      # (C, H, W)
    channel_mean = mean_arr.mean(axis=(1, 2))             # like SetMean

    net = api.Classifier(model_file, trained_file,
                         mean=channel_mean, raw_scale=255.0,
                         channel_swap=(2, 1, 0))
    image = api.io.load_image(image_file)
    probs = net.predict([image], oversample=False)[0]
    with open(label_file) as f:
        labels = [line.strip() for line in f if line.strip()]
    top = np.argsort(probs)[::-1][:5]
    return [(float(probs[i]),
             labels[i] if i < len(labels) else str(int(i)))
            for i in top]
)PY";

static int fail(const char* msg) {
  if (PyErr_Occurred()) PyErr_Print();
  std::fprintf(stderr, "%s\n", msg);
  Py_Finalize();
  return 1;
}

int main(int argc, char** argv) {
  if (argc != 6) {
    std::fprintf(stderr,
                 "Usage: %s deploy.prototxt network.caffemodel"
                 " mean.binaryproto labels.txt img.jpg\n",
                 argv[0]);
    return 1;
  }
  Py_Initialize();

  PyObject* module = PyImport_AddModule("__main__");
  PyObject* globals = PyModule_GetDict(module);
  if (!PyRun_String(kClassifySource, Py_file_input, globals, globals))
    return fail("failed to initialize the embedded framework");

  PyObject* fn = PyDict_GetItemString(globals, "classify");
  if (!fn) return fail("classify() not defined");

  std::printf("---------- Prediction for %s ----------\n", argv[5]);
  PyObject* result = PyObject_CallFunction(fn, "sssss", argv[1], argv[2],
                                           argv[3], argv[4], argv[5]);
  if (!result) return fail("classification failed");

  for (Py_ssize_t i = 0; i < PyList_Size(result); ++i) {
    PyObject* pair = PyList_GetItem(result, i);
    double confidence = PyFloat_AsDouble(PyTuple_GetItem(pair, 0));
    const char* label = PyUnicode_AsUTF8(PyTuple_GetItem(pair, 1));
    // reference output format: "0.5009 - \"n03482405 hamper\""
    std::printf("%.4f - \"%s\"\n", confidence, label);
  }
  Py_DECREF(result);
  Py_Finalize();
  return 0;
}

#!/bin/sh
# Build the native classification host and run it on the given arguments
# (defaults: compile only). RRAM_TPU_ROOT must point at the repo root so
# the embedded interpreter can import the framework.
set -e
HERE=$(dirname "$(readlink -f "$0")")
g++ -O2 "$HERE/classification.cpp" -o "$HERE/classification" \
    $(python3-config --includes) $(python3-config --embed --ldflags)
echo "built $HERE/classification"
if [ "$#" -ge 5 ]; then
    RRAM_TPU_ROOT="${RRAM_TPU_ROOT:-$HERE/../..}" "$HERE/classification" "$@"
fi

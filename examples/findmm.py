#!/usr/bin/env python
"""Print per-parameter min/max of a trained model — parity with the
reference's examples/cifar10/findmm.py."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from rram_caffe_simulation_tpu import api as caffe  # noqa: E402


def main(argv):
    if len(argv) != 3:
        print(f"usage: {argv[0]} <net.prototxt> <weights.caffemodel>")
        return 1
    net = caffe.Net(argv[1], argv[2], caffe.TEST)
    for name, blobs in net.params.items():
        for i, blob in enumerate(blobs):
            print(f"{name}[{i}]: min = {blob.data.min():g}, "
                  f"max = {blob.data.max():g}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))

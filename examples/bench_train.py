"""ImageNet-class TRAINING throughput on TPU — the reference's headline
perf metric (CaffeNet train at 193-267 img/s on a K40,
/root/reference/docs/performance_hardware.md:17-25).

Trains the real zoo train_val graphs through the Solver path: the TRAIN
Data layer is swapped for a shape-equal Input declaration fed from one
pre-staged device-resident batch (inputize/fixed_feed — the same feed
profile_train.py captures, so bench wall-clock and profile attribution
measure the SAME program; --dummy-data swaps in the older in-graph
DummyData generator instead), and throughput is steady-state img/s over
a timed window after a compile/warmup chunk.
Also reports achieved model FLOP/s — 3 x analytic forward FLOPs per
step (fwd + two bwd matmul passes) — and MFU against the chip's peak.

    python examples/bench_train.py \
        --model models/bvlc_reference_caffenet/train_val.prototxt \
        --batch 256 --iters 60 --chunk 60 --compute-dtype bfloat16
"""
import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..")
sys.path.insert(0, REPO)


def _num_classes(net_param):
    """num_output of the layer feeding the softmax loss (uniform label
    range; constant labels collapse the loss to 0 in one step)."""
    producers = {}
    for lp in net_param.layer:
        for t in lp.top:
            producers[t] = lp
    for lp in net_param.layer:
        if lp.type == "SoftmaxWithLoss" and lp.bottom:
            prod = producers.get(lp.bottom[0])
            if prod is not None and prod.type == "InnerProduct":
                return int(prod.inner_product_param.num_output)
    return 1000


def dummyize(net_param, batch):
    """Replace TRAIN-phase Data layers with shape-equivalent DummyData
    (gaussian images, uniform labels) so the step is chip-resident."""
    n_classes = _num_classes(net_param)
    for lp, dshape, lshape in list(_train_data_layers(net_param, batch)):
        lp.type = "DummyData"
        dp = lp.dummy_data_param
        del dp.shape[:]
        s = dp.shape.add()
        s.dim.extend(dshape)
        if lshape is not None:
            s = dp.shape.add()
            s.dim.extend(lshape)
        f = dp.data_filler.add()
        f.type = "gaussian"
        f.std = 1.0
        if lshape is not None:
            f = dp.data_filler.add()
            f.type = "uniform"
            f.min = 0.0
            f.max = n_classes - 0.001  # astype(int32) truncates
        lp.ClearField("data_param")
        lp.ClearField("transform_param")
    return net_param


def _train_data_layers(net_param, batch):
    """Yield (layer, data_shape, label_shape_or_None) for every
    TRAIN-phase Data layer — the selection/shape logic dummyize and
    inputize share."""
    from rram_caffe_simulation_tpu.proto import pb
    for lp in net_param.layer:
        if lp.type != "Data":
            continue
        phases = [inc.phase for inc in lp.include] or [pb.TRAIN]
        if pb.TRAIN not in phases:
            continue
        crop = lp.transform_param.crop_size or 224
        yield (lp, (batch, 3, crop, crop),
               (batch,) if len(lp.top) > 1 else None)


def inputize(net_param, batch):
    """Replace TRAIN-phase Data layers with shape-equal Input
    declarations and return (net_param, batch_spec): the feed comes from
    a once-device-put batch (see fixed_feed), so the profiled/benched
    step contains no in-graph input generation (the DummyData RNG ops
    polluted 6-15% of the r4 per-HLO attributions)."""
    n_classes = _num_classes(net_param)
    spec = {}
    for lp, dshape, lshape in list(_train_data_layers(net_param, batch)):
        lp.type = "Input"
        s = lp.input_param.shape.add()
        s.dim.extend(dshape)
        spec[lp.top[0]] = ("image", dshape)
        if lshape is not None:
            s = lp.input_param.shape.add()
            s.dim.extend(lshape)
            spec[lp.top[1]] = ("label", lshape, n_classes)
        lp.ClearField("data_param")
        lp.ClearField("transform_param")
    return net_param, spec


def fixed_feed(spec, seed=0):
    """One fixed batch per the inputize spec, drawn once and device_put
    ONCE: every step_fused pull returns the same device buffers, so the
    per-chunk jnp.stack is a device-side broadcast — no repeated H2D of
    identical data inside the profiled region."""
    import numpy as np
    import jax
    rng = np.random.RandomState(seed)
    batch = {}
    for top, info in spec.items():
        if info[0] == "image":
            batch[top] = rng.randn(*info[1]).astype(np.float32)
        else:
            batch[top] = rng.randint(
                0, info[2], size=info[1]).astype(np.int32)
    staged = {}

    def feed():
        if not staged:
            staged.update({k: jax.device_put(v) for k, v in batch.items()})
            batch.clear()   # release the host copy (~150 MB at b256)
        return staged
    return feed


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", required=True,
                   help="train_val prototxt (TRAIN Data layer is swapped "
                        "for DummyData)")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--iters", type=int, default=60,
                   help="timed iterations (after one warmup chunk)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed windows; min is reported (the tunneled "
                        "dispatch path has large run-to-run jitter)")
    p.add_argument("--chunk", type=int, default=60,
                   help="iterations scanned per device dispatch")
    p.add_argument("--compute-dtype", default="",
                   help="e.g. bfloat16; empty = float32")
    p.add_argument("--dummy-data", action="store_true",
                   help="generate inputs in-graph (DummyData) instead "
                        "of the default pre-staged Input feed; the "
                        "in-graph RNG then rides the timed step")
    p.add_argument("--peak-tflops", type=float, default=197.0,
                   help="chip peak for the MFU column (v5e bf16 = 197)")
    p.add_argument("--cache-dir", default="",
                   help="cold-start cache root (overrides "
                        "RRAM_TPU_CACHE_DIR): the step's XLA compile "
                        "persists under <dir>/xla, so a second "
                        "same-config run skips compilation entirely")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable JSON line")
    args = p.parse_args(argv)
    args.iters = max(args.iters // args.chunk, 1) * args.chunk

    os.chdir(REPO)
    import jax
    from rram_caffe_simulation_tpu import cache as rcache
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.utils.io import read_net_param
    from rram_caffe_simulation_tpu.tools.summarize import net_fwd_flops

    rcache.enable_compilation_cache(args.cache_dir or None)
    setup_stats = rcache.SetupStats()
    if rcache.cache_dir():
        # the Input feed decodes no dataset: with a cache root active
        # that is "unused", not "disabled" (= no cache dir configured)
        setup_stats.dataset = "unused"
    t_setup0 = time.perf_counter()
    netp = read_net_param(args.model)
    if args.dummy_data:
        netp = dummyize(netp, args.batch)
        feed = None
    else:
        # default: device-resident fixed batch through Input layers —
        # the benched program matches the profiled one (profile_train)
        netp, spec = inputize(netp, args.batch)
        feed = fixed_feed(spec)
    sp = pb.SolverParameter()
    sp.net_param.CopyFrom(netp)
    sp.base_lr = 0.001  # throughput run; random labels diverge at 0.01
    sp.momentum = 0.9
    sp.weight_decay = 0.0005
    sp.lr_policy = "fixed"
    sp.type = "SGD"
    sp.max_iter = 10 ** 9
    sp.display = 0
    sp.random_seed = 7
    solver = Solver(sp, train_feed=feed,
                    compute_dtype=args.compute_dtype or None)

    fwd_flops, _ = net_fwd_flops(solver.net)  # at the built batch size
    # sync on ONE leaf: the step is a single device program, so one
    # output completing means all did — block_until_ready over the whole
    # tree costs a round trip per leaf on a tunneled runtime
    sync = lambda: jax.block_until_ready(
        jax.tree.leaves(solver.params)[0])
    t0 = time.perf_counter()
    with setup_stats.timed_compile():
        solver.step_fused(args.chunk, chunk=args.chunk)  # compile + warmup
        sync()
    setup_s = time.perf_counter() - t0

    dt = float("inf")
    for _ in range(max(args.repeats, 1)):
        t0 = time.perf_counter()
        solver.step_fused(args.iters, chunk=args.chunk)
        sync()
        dt = min(dt, time.perf_counter() - t0)

    img_s = args.batch * args.iters / dt
    step_ms = dt / args.iters * 1e3
    train_tflops = 3 * fwd_flops * args.iters / dt / 1e12
    mfu = train_tflops / args.peak_tflops
    loss = solver.smoothed_loss
    # HBM-floor accounting (the sweep bench's bytes_per_step_est twin):
    # resident state read + written once per step — masters and
    # momentum (activations excluded: shape-dependent and largely
    # fused) — plus the per-step input batch read.
    bytes_step = 2 * sum(int(a.nbytes) for a in jax.tree.leaves(
        (solver.params, solver.history)))
    if feed is not None:
        bytes_step += sum(int(v.nbytes) for v in feed().values())
    setup_stats.bytes_per_step = bytes_step
    achieved_gb_s = bytes_step * args.iters / dt / 1e9
    rec = {
        "model": os.path.basename(os.path.dirname(args.model)) or
                 args.model,
        "batch": args.batch,
        "compute_dtype": args.compute_dtype or "float32",
        "feed": "dummy" if args.dummy_data else "input",
        "img_per_s": round(img_s, 1),
        "step_ms": round(step_ms, 3),
        "fwd_gflops_per_batch": round(fwd_flops / 1e9, 2),
        "achieved_tflops": round(train_tflops, 2),
        "mfu_vs_peak": round(mfu, 4),
        "bytes_per_step_est": bytes_step,
        "achieved_bandwidth_gb_s": round(achieved_gb_s, 2),
        "peak_tflops": args.peak_tflops,
        "iters": args.iters,
        "chunk": args.chunk,
        "repeats": max(args.repeats, 1),
        "compile_warmup_s": round(setup_s, 1),
        # the structured cold-start breakdown (observe `setup` record):
        # decode_seconds is the host-side input staging (zero for the
        # default pre-staged Input feed), compile_seconds the jit
        # compile+warmup chunk, cache.compile hit|miss|partial|disabled
        "setup": setup_stats.record(
            setup_s=time.perf_counter() - t_setup0),
        "final_loss": round(float(loss), 4),
        "backend": jax.default_backend(),
    }
    if args.json:
        print(json.dumps(rec))
    else:
        print(f"{rec['model']}  batch {args.batch}  "
              f"{rec['compute_dtype']}")
        print(f"  {img_s:,.1f} img/s   {step_ms:.2f} ms/step   "
              f"{train_tflops:.1f} TFLOP/s achieved   "
              f"MFU {100 * mfu:.1f}% of {args.peak_tflops:.0f} TF peak")
        print(f"  (fwd {fwd_flops / 1e9:.1f} GFLOPs/batch, train = 3x; "
              f"compile+warmup {setup_s:.1f}s, final loss "
              f"{float(loss):.3f}, backend {rec['backend']})")
        from rram_caffe_simulation_tpu.observe import setup_line
        print("  " + setup_line(rec["setup"]))
    return rec


if __name__ == "__main__":
    main()

"""Generate the siamese LeNet train/test prototxt with the net_spec DSL.

Same capability as reference examples/siamese/mnist_siamese_train_test.prototxt:
a 2-channel pair Datum is sliced into the two images, each runs through a
LeNet-style tower whose weights are SHARED by param name (conv1_w, ...,
feat_w), and a ContrastiveLoss (margin 1) pulls same-class embeddings
together and pushes different-class ones apart. The twin tower exercises
the net builder's named-param sharing table.

Run:  python examples/siamese/generate.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from rram_caffe_simulation_tpu.api.net_spec import NetSpec, layers as L, params as P  # noqa: E402
from rram_caffe_simulation_tpu.proto import pb  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))


def shared_param(stem):
    """lr_mults per reference siamese recipe; sharing is by param name."""
    return [dict(name=f"{stem}_w", lr_mult=1),
            dict(name=f"{stem}_b", lr_mult=2)]


def tower(n, data, suffix=""):
    """LeNet embedding tower; `suffix` distinguishes blob/layer names while
    param names stay identical so both towers share weights."""
    s = suffix

    n["conv1" + s] = L.Convolution(
        data, num_output=20, kernel_size=5, stride=1,
        param=shared_param("conv1"),
        weight_filler=dict(type="xavier"),
        bias_filler=dict(type="constant"))
    n["pool1" + s] = L.Pooling(n["conv1" + s], pool=P.Pooling.MAX,
                               kernel_size=2, stride=2)
    n["conv2" + s] = L.Convolution(
        n["pool1" + s], num_output=50, kernel_size=5, stride=1,
        param=shared_param("conv2"),
        weight_filler=dict(type="xavier"),
        bias_filler=dict(type="constant"))
    n["pool2" + s] = L.Pooling(n["conv2" + s], pool=P.Pooling.MAX,
                               kernel_size=2, stride=2)
    n["ip1" + s] = L.InnerProduct(
        n["pool2" + s], num_output=500, param=shared_param("ip1"),
        weight_filler=dict(type="xavier"),
        bias_filler=dict(type="constant"))
    n["relu1" + s] = L.ReLU(n["ip1" + s], in_place=True)
    n["ip2" + s] = L.InnerProduct(
        n["ip1" + s], num_output=10, param=shared_param("ip2"),
        weight_filler=dict(type="xavier"),
        bias_filler=dict(type="constant"))
    n["feat" + s] = L.InnerProduct(
        n["ip2" + s], num_output=2, param=shared_param("feat"),
        weight_filler=dict(type="xavier"),
        bias_filler=dict(type="constant"))
    return n["feat" + s]


def train_test(train_source, test_source, batch=64):
    n = NetSpec()
    n.pair_data, n.sim = L.Data(
        ntop=2, name="pair_data",
        include=dict(phase=pb.TRAIN),
        transform_param=dict(scale=0.00390625),
        data_param=dict(source=train_source, batch_size=batch,
                        backend=P.Data.LMDB))
    n.data, n.data_p = L.Slice(n.pair_data, ntop=2, name="slice_pair",
                               slice_param=dict(slice_dim=1))
    feat = tower(n, n.data)
    feat_p = tower(n, n.data_p, suffix="_p")
    n.loss = L.ContrastiveLoss(feat, feat_p, n.sim,
                               contrastive_loss_param=dict(margin=1.0))
    proto = n.to_proto()
    proto.name = "mnist_siamese_train_test"
    test_data = pb.LayerParameter()
    test_data.name = "pair_data"
    test_data.type = "Data"
    test_data.top.extend(["pair_data", "sim"])
    test_data.include.add().phase = pb.TEST
    test_data.transform_param.scale = 0.00390625
    test_data.data_param.source = test_source
    test_data.data_param.batch_size = batch
    test_data.data_param.backend = pb.DataParameter.LMDB
    proto.layer.insert(1, test_data)
    return proto


SOLVER = """\
net: "examples/siamese/mnist_siamese_train_test.prototxt"
test_iter: 4
test_interval: 500
base_lr: 0.01
momentum: 0.9
weight_decay: 0.0000
lr_policy: "inv"
gamma: 0.0001
power: 0.75
display: 100
max_iter: 2000
snapshot: 2000
snapshot_prefix: "examples/siamese/snapshots/mnist_siamese"
"""


def main():
    proto = train_test("examples/siamese/siamese_train_lmdb",
                       "examples/siamese/siamese_test_lmdb")
    with open(os.path.join(HERE, "mnist_siamese_train_test.prototxt"),
              "w") as f:
        f.write(str(proto))
    with open(os.path.join(HERE, "mnist_siamese_solver.prototxt"), "w") as f:
        f.write(SOLVER)
    print("wrote mnist_siamese_train_test.prototxt, mnist_siamese_solver.prototxt")


if __name__ == "__main__":
    main()

"""End-to-end siamese example: pair converter -> LMDB -> shared-weight twin
towers -> ContrastiveLoss training -> embedding-separation check.

Same workflow as the reference examples/siamese/ (convert_mnist_siamese_data
+ train_mnist_siamese.sh), driven on the digits corpus built by
examples/mnist/make_digits_dataset.py (real MNIST needs the network).

Usage: python examples/siamese/run_siamese.py [--iters N]
"""
import argparse
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)


def ensure_datasets():
    digits = os.path.join(REPO, "examples", "mnist")
    if not os.path.exists(os.path.join(digits, "train-images-idx3-ubyte")):
        sys.path.insert(0, digits)
        from make_digits_dataset import build
        build(digits)
    from rram_caffe_simulation_tpu.tools.converters import (
        convert_mnist_siamese)
    idx_stem = {"train": ("train-images-idx3", "train-labels-idx1"),
                "test": ("t10k-images-idx3", "t10k-labels-idx1")}
    for split, (im, lb) in idx_stem.items():
        out = os.path.join(HERE, f"siamese_{split}_lmdb")
        if not os.path.exists(out):
            n = convert_mnist_siamese(
                os.path.join(digits, f"{im}-ubyte"),
                os.path.join(digits, f"{lb}-ubyte"), out)
            print(f"siamese_{split}_lmdb: {n} pair records")


def embedding_separation(solver):
    """Mean same-class vs different-class distance of `feat` over a few
    test batches; a trained siamese net must separate the two."""
    import jax.numpy as jnp
    net = solver.test_nets[0]
    feed = solver.test_feeds[0]
    same, diff = [], []
    for _ in range(4):
        batch = {k: jnp.asarray(v) for k, v in feed().items()}
        blobs, _ = net.apply(solver.params, batch)
        d = np.asarray(jnp.sum(
            (blobs["feat"] - blobs["feat_p"]) ** 2, axis=1)) ** 0.5
        sim = np.asarray(batch["sim"]).reshape(-1)
        same.extend(d[sim == 1])
        diff.extend(d[sim == 0])
    return float(np.mean(same)), float(np.mean(diff))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=2000)
    args = ap.parse_args()

    ensure_datasets()
    import subprocess
    subprocess.run([sys.executable, os.path.join(HERE, "generate.py")],
                   check=True)

    os.makedirs(os.path.join(HERE, "snapshots"), exist_ok=True)
    os.chdir(REPO)
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.utils.io import read_solver_param
    param = read_solver_param(
        os.path.join(HERE, "mnist_siamese_solver.prototxt"))
    param.max_iter = args.iters
    solver = Solver(param)
    solver.step(args.iters)
    same, diff = embedding_separation(solver)
    print(f"mean embedding distance: same-class {same:.3f}, "
          f"different-class {diff:.3f}, ratio {diff / max(same, 1e-9):.2f}x")
    solver.snapshot()


if __name__ == "__main__":
    main()

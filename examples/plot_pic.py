#!/usr/bin/env python
"""Accuracy/loss table + plot from a training log — parity with the
reference's examples/cifar10/plot_pic.py (same regex scrape of
`accuracy = X ... loss = Y` pairs, same table format, matplotlib plot when
DISPLAY is available)."""
import argparse
import os
import re
import sys

import numpy as np

p = argparse.ArgumentParser()
p.add_argument("log", help="the log file")
p.add_argument("-n", "--no-plot", help="do not plot", action="store_true")
args = p.parse_args()

with open(args.log) as f:
    content = f.read()

m = re.search(r"test_interval: (\d+)", content)
assert m is not None, "log must contain the solver config"
test_interval = int(m.group(1))

pattern = re.compile(r"accuracy = (?P<acc>[\d.]+).*?loss = (?P<loss>[\d.]+)",
                     re.DOTALL)
acc_list, loss_list = [], []
for match in pattern.finditer(content):
    acc_list.append(float(match.group("acc")))
    loss_list.append(float(match.group("loss")))

print("iter     accuracy    loss")
for it, acc, loss in zip(np.arange(len(acc_list)) * test_interval,
                         acc_list, loss_list):
    print(f"{it:<8}    {acc:<12}    {loss:<12}")

if not args.no_plot and os.environ.get("DISPLAY"):
    from matplotlib import pyplot as plt
    fig, ax1 = plt.subplots()
    xs = np.arange(len(acc_list)) * test_interval
    ax1.plot(xs, acc_list, "b-", label="accuracy")
    ax2 = ax1.twinx()
    ax2.plot(xs, loss_list, "r-", label="loss")
    ax1.set_xlabel("iteration")
    ax1.set_ylabel("accuracy")
    ax2.set_ylabel("loss")
    plt.show()

#!/usr/bin/env python
"""pycaffe extension-point example (reference examples/pycaffe):

1. trains `linreg.prototxt` — whose loss is the PythonLayer in
   `pyloss.py` — with the pycaffe-style SGDSolver facade, showing
   host-side Python layers composing with the jitted training loop;
2. checks the Python loss + its backward against the built-in
   EuclideanLoss layer on the same data (same contract, two
   implementations);
3. regenerates a prototxt programmatically with the net_spec DSL, the
   reference caffenet.py workflow.

    python examples/pycaffe/run_pycaffe.py
"""
import os
import sys

import numpy as np

# PythonLayers run host-side (pure_callback); tunneled PJRT backends have
# no host-callback channel, so this example pins the CPU backend (the env
# var alone is not enough where a sitecustomize registers the tunnel
# backend — the config update below overrides it, like tests/conftest.py).
# On a directly-attached TPU runtime the callback path works as-is.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)
sys.path.insert(0, HERE)  # pyloss must be importable by module name

from rram_caffe_simulation_tpu import api  # noqa: E402
from rram_caffe_simulation_tpu.api.net_spec import NetSpec, layers as L, params as P  # noqa: E402
from rram_caffe_simulation_tpu.proto import pb  # noqa: E402
from google.protobuf import text_format  # noqa: E402


def train_linreg():
    sp = pb.SolverParameter()
    sp.train_net = os.path.join(HERE, "linreg.prototxt")
    sp.base_lr = 0.05
    sp.lr_policy = "fixed"
    sp.display = 20
    sp.max_iter = 100
    sp.random_seed = 5
    sp.snapshot_prefix = os.path.join(HERE, "linreg")
    solver = api.SGDSolver(sp)
    net = solver.net  # materialize the pycaffe view before stepping
    solver.step(1)
    l0 = float(net.blobs["loss"].data.reshape(-1)[0])
    solver.step(99)
    l1 = float(net.blobs["loss"].data.reshape(-1)[0])
    print(f"linreg python-loss: iter 1 {l0:.4f} -> iter 100 {l1:.4f}")
    assert l1 < l0 * 0.2, "training through the PythonLayer must converge"


def check_against_builtin():
    """pyloss == built-in EuclideanLoss, forward and backward."""
    import pyloss
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a = rng.randn(10, 6).astype(np.float32)
    b = rng.randn(10, 6).astype(np.float32)

    net_text = """
layer { name: "data" type: "Input" top: "a" top: "b"
  input_param { shape { dim: 10 dim: 6 } shape { dim: 10 dim: 6 } } }
layer { name: "loss" type: "%s" bottom: "a" bottom: "b" top: "loss"
  %s loss_weight: 1 }
"""
    py = pb.NetParameter()
    text_format.Parse(net_text % (
        "Python", 'python_param { module: "pyloss" '
        'layer: "EuclideanLossLayer" }'), py)
    ref = pb.NetParameter()
    text_format.Parse(net_text % ("EuclideanLoss", ""), ref)

    from rram_caffe_simulation_tpu.net import Net
    net_py = Net(py, pb.TRAIN)
    net_ref = Net(ref, pb.TRAIN)
    p0 = net_py.init(jax.random.PRNGKey(0))
    batch = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
    loss_py = float(net_py.apply(p0, batch)[1])
    loss_ref = float(net_ref.apply(p0, batch)[1])
    np.testing.assert_allclose(loss_py, loss_ref, rtol=1e-5)

    ga = jax.grad(lambda x: net_py.apply(p0, {"a": x, "b": batch["b"]})[1])(
        batch["a"])
    gr = jax.grad(lambda x: net_ref.apply(p0, {"a": x, "b": batch["b"]})[1])(
        batch["a"])
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gr), rtol=1e-4)
    print(f"python EuclideanLoss == built-in: loss {loss_py:.4f}, "
          "grads match")


def generate_with_net_spec():
    """The caffenet.py workflow: compose a net in Python, emit prototxt."""
    n = NetSpec()
    n.data, n.label = L.DummyData(
        ntop=2, shape=[dict(dim=[8, 1, 8, 8]), dict(dim=[8])],
        data_filler=[dict(type="gaussian"), dict(type="constant")])
    n.conv = L.Convolution(n.data, kernel_size=3, num_output=4,
                           weight_filler=dict(type="xavier"))
    n.relu = L.ReLU(n.conv, in_place=True)
    n.pool = L.Pooling(n.conv, pool=P.Pooling.MAX, kernel_size=2, stride=2)
    n.ip = L.InnerProduct(n.pool, num_output=10,
                          weight_filler=dict(type="xavier"))
    n.loss = L.SoftmaxWithLoss(n.ip, n.label)
    path = os.path.join(HERE, "generated_net.prototxt")
    with open(path, "w") as f:
        f.write(str(n.to_proto()))
    # the generated prototxt must round-trip into a buildable net
    from rram_caffe_simulation_tpu.net import Net
    from rram_caffe_simulation_tpu.utils.io import read_net_param
    import jax
    net = Net(read_net_param(path), pb.TRAIN)
    params = net.init(jax.random.PRNGKey(0))
    _, loss = net.apply(params, rng=jax.random.PRNGKey(1))
    print(f"net_spec-generated prototxt builds and runs (loss "
          f"{float(loss):.3f})")


def main():
    check_against_builtin()
    train_linreg()
    generate_with_net_spec()
    print("pycaffe examples OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Python EuclideanLossLayer (reference examples/pycaffe/layers/pyloss.py
parity): the same numeric contract as the built-in EuclideanLoss layer,
implemented entirely host-side through the PythonLayer extension point —
the class interface for developing layers in Python.

Under jit the forward runs via pure_callback and the backward via the
custom_vjp bridge calling this class's backward() (ops/extra.py
PythonLayer), so the layer still composes with jax.grad and the Solver.
"""
import numpy as np


class EuclideanLossLayer:
    def setup(self, bottom, top):
        if len(bottom) != 2:
            raise Exception("Need two inputs to compute distance.")

    def reshape(self, bottom, top):
        if bottom[0].data.size != bottom[1].data.size:
            raise Exception("Inputs must have the same dimension.")
        self.diff = np.zeros_like(bottom[0].data, dtype=np.float32)
        top[0].reshape(1)

    def forward(self, bottom, top):
        self.diff[...] = bottom[0].data - bottom[1].data
        top[0].data[...] = np.sum(self.diff ** 2) / bottom[0].shape[0] / 2.0

    def backward(self, top, propagate_down, bottom):
        for i in range(2):
            if not propagate_down[i]:
                continue
            sign = 1 if i == 0 else -1
            bottom[i].diff[...] = (sign * self.diff * top[0].diff.reshape(())
                                   / bottom[i].shape[0])

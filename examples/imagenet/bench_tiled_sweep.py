"""ImageNet-resolution tiled-crossbar fault sweep bench (ROADMAP item 1
deliverable / ISSUE 11 acceptance; ISSUE 18 adds the conv row): a
VGG-class layer at 224x224 input resolution, its weight split across
multiple physical crossbar tiles (fault/mapping.py), trained as a
config-SHARDED Monte-Carlo fault sweep with the per-tile fault census
flowing through the observe schema.

Two nets, picked by ``--net``:

``vgg-fc`` (default) — one strided conv + pool feeding an fc6-style
InnerProduct, so the bench runs anywhere, but the LAYER is the real
thing: 224x224x3 input, an FC crossbar bigger than one physical array
(stored (512, 784); under the default ``cells=256x256`` mapping that
is a 2x4 = 8-tile grid, each tile with its own independent fault draw
and its own ADC on the analog partial sums).

``vgg-conv`` (ISSUE 18; ISSUE 19 adds the implicit row) — a conv stack
with EVERY weight on a crossbar (``failure_pattern { conv_also:
true }``): conv1 8x8/8 and conv2 3x3 kernels mapped over their im2col
(C*kh*kw, C_out) views (under the conv default ``cells=128x128``:
conv1 view 192x16 -> 2x1 grid, conv2 view 144x32 -> 2x1 grid) plus an
FC head. The conv im2col GEMM is timed in ALL THREE operand modes —
``premat`` (patches materialized once, default), ``tilewise``
(K-slabs extracted inside the jax-engine tile loop) and ``implicit``
(the operand block gathered in-kernel / per-slab from the raw
activation; the patch matrix never exists in HBM) — and the row
records each mode's resolved state, ``bytes_per_step_est`` HBM floor
and ``conv_patch_bytes`` patch-operand share. ``--conv-im2col`` picks
the PRIMARY row's mode (default: the runner's resolution chain —
Solver knob, then the RRAM_CONV_IM2COL env fallback, then premat).

The sweep's config axis lays over every visible device
(``TILED_BENCH_MESH``, default ``config=all``) as ONE GSPMD program —
the PR 9 pod path — and metrics records carry ``fault.per_tile``
(schema-validated here before the row is printed).

Environment knobs:

  TILED_BENCH_CONFIGS   sweep lanes (default 8)
  TILED_BENCH_STEPS     timed steps (default 30)
  TILED_BENCH_CHUNK     scan chunk (default 10)
  TILED_BENCH_BATCH     images per step per config (default 8)
  TILED_BENCH_TILES     TileSpec (default cells=256x256;
                        vgg-conv default cells=128x128)
  TILED_BENCH_MESH      mesh spec (default config=all; '' = no mesh)
  TILED_BENCH_ENGINE    sweep engine, "jax" | "pallas" (default jax)
  TILED_BENCH_DEVICES   on CPU hosts: force N virtual devices
                        (default 4; set before JAX initializes)

Prints exactly ONE JSON line on stdout.
"""
import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)

# on a CPU host, shard the config axis over virtual devices so the row
# exercises the REAL config-sharded program (chips > 1); harmless when
# XLA_FLAGS is already set or a real accelerator is attached
_NDEV = int(os.environ.get("TILED_BENCH_DEVICES", "4"))
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", "") and _NDEV > 1 \
        and os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_NDEV}")

N_CONFIGS = int(os.environ.get("TILED_BENCH_CONFIGS", "8"))
STEPS = int(os.environ.get("TILED_BENCH_STEPS", "30"))
CHUNK = int(os.environ.get("TILED_BENCH_CHUNK", "10"))
BATCH = int(os.environ.get("TILED_BENCH_BATCH", "8"))
MESH = os.environ.get("TILED_BENCH_MESH", "config=all")
ENGINE = os.environ.get("TILED_BENCH_ENGINE", "jax")

NET_FC = """
name: "VGGTiledHead"
layer { name: "data" type: "Input" top: "data" top: "label"
  input_param { shape { dim: %(batch)d dim: 3 dim: 224 dim: 224 }
                shape { dim: %(batch)d dim: 10 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 16 kernel_size: 8 stride: 8
    weight_filler { type: "gaussian" std: 0.01 }
    bias_filler { type: "constant" value: 0 } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 4 stride: 4 } }
layer { name: "fc6" type: "InnerProduct" bottom: "pool1" top: "fc6"
  inner_product_param { num_output: 512
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "relu6" type: "ReLU" bottom: "fc6" top: "fc6" }
layer { name: "fc7" type: "InnerProduct" bottom: "fc6" top: "fc7"
  inner_product_param { num_output: 10
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "fc7"
  bottom: "label" top: "loss" }
"""

# the ISSUE 18 conv row: every weight on a crossbar (conv_also), the
# conv kernels tiled over their im2col views
NET_CONV = """
name: "VGGTiledConv"
layer { name: "data" type: "Input" top: "data" top: "label"
  input_param { shape { dim: %(batch)d dim: 3 dim: 224 dim: 224 }
                shape { dim: %(batch)d dim: 10 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 16 kernel_size: 8 stride: 8
    weight_filler { type: "gaussian" std: 0.01 }
    bias_filler { type: "constant" value: 0 } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "conv2" type: "Convolution" bottom: "conv1" top: "conv2"
  convolution_param { num_output: 32 kernel_size: 3 pad: 1
    weight_filler { type: "gaussian" std: 0.02 }
    bias_filler { type: "constant" value: 0 } } }
layer { name: "relu2" type: "ReLU" bottom: "conv2" top: "conv2" }
layer { name: "pool2" type: "Pooling" bottom: "conv2" top: "pool2"
  pooling_param { pool: MAX kernel_size: 4 stride: 4 } }
layer { name: "fc6" type: "InnerProduct" bottom: "pool2" top: "fc6"
  inner_product_param { num_output: 128
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "relu6" type: "ReLU" bottom: "fc6" top: "fc6" }
layer { name: "fc7" type: "InnerProduct" bottom: "fc6" top: "fc7"
  inner_product_param { num_output: 10
    weight_filler { type: "gaussian" std: 0.05 }
    bias_filler { type: "constant" value: 0 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "fc7"
  bottom: "label" top: "loss" }
"""


class _Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--net", choices=("vgg-fc", "vgg-conv"),
                    default="vgg-fc",
                    help="vgg-fc: tiled FC crossbar (ISSUE 11 row); "
                         "vgg-conv: conv stack with every weight on a "
                         "crossbar via im2col tiling (ISSUE 18 row)")
    ap.add_argument("--conv-im2col",
                    choices=("premat", "tilewise", "implicit"),
                    default=None,
                    help="conv im2col operand mode for the primary "
                         "timed run (default: the runner's resolution "
                         "chain — Solver knob / RRAM_CONV_IM2COL env "
                         "/ premat); the vgg-conv row times the other "
                         "modes too for the comparison columns")
    args = ap.parse_args()
    conv_net = args.net == "vgg-conv"
    tiles = os.environ.get("TILED_BENCH_TILES") or (
        "cells=128x128" if conv_net else "cells=256x256")

    import numpy as np
    from google.protobuf import text_format

    import jax

    from rram_caffe_simulation_tpu.fault.mapping import (
        TileSpec, crossbar_view_shape)
    from rram_caffe_simulation_tpu.observe import schema as obs_schema
    from rram_caffe_simulation_tpu.parallel import SweepRunner
    from rram_caffe_simulation_tpu.parallel.mesh import mesh_from_spec
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver

    rng = np.random.RandomState(5)
    data = rng.randn(BATCH, 3, 224, 224).astype(np.float32)
    label = rng.randn(BATCH, 10).astype(np.float32)

    def build_solver():
        sp = pb.SolverParameter()
        text_format.Parse((NET_CONV if conv_net else NET_FC)
                          % {"batch": BATCH}, sp.net_param)
        sp.base_lr = 0.0002   # stable on the random-data proxy batch
        sp.lr_policy = "fixed"
        sp.max_iter = 10 ** 9
        sp.display = 0
        sp.random_seed = 11
        sp.snapshot_prefix = "/tmp/tiled_imagenet_bench"
        # lifetimes sized so cells BREAK inside the timed window — the
        # per-tile census then shows real spatial structure, not zeros
        sp.failure_pattern.type = "gaussian"
        sp.failure_pattern.mean = STEPS * 50.0
        sp.failure_pattern.std = STEPS * 15.0
        if conv_net:
            sp.failure_pattern.conv_also = True
        sp.rram_forward.sigma = 0.0
        sp.rram_forward.adc_bits = 4     # the per-tile ADC width
        solver = Solver(sp, train_feed=lambda: {"data": data,
                                                "label": label},
                        tile_spec=tiles)
        sink = _Sink()
        solver.enable_metrics(sink)
        sp.display = CHUNK   # records at chunk boundaries
        return solver, sink

    def timed_run(solver, conv_im2col=None):
        """Compile + warm up, then time STEPS sweep iterations."""
        mesh = mesh_from_spec(MESH) if MESH else None
        t0 = time.perf_counter()
        runner = SweepRunner(solver, n_configs=N_CONFIGS, mesh=mesh,
                             pipeline_depth=0, engine=ENGINE,
                             conv_im2col=conv_im2col)
        runner.step(CHUNK, chunk=CHUNK)   # compile + warmup
        jax.block_until_ready(runner.params)
        setup_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        runner.step(STEPS, chunk=CHUNK)
        jax.block_until_ready(runner.params)
        dt = time.perf_counter() - t0
        return runner, setup_s, dt

    solver, sink = build_solver()
    tspec = TileSpec.parse(tiles)
    flat = solver._flat(solver.params)
    grids, views = {}, {}
    for k, v in flat.items():
        if k not in solver._fault_keys or v.ndim < 2:
            continue
        grids[k] = list(tspec.grid(v.shape))
        if v.ndim > 2:
            # conv kernels tile over their im2col (K, N) view
            views[k] = list(crossbar_view_shape(v.shape))

    runner, setup_s, dt = timed_run(solver,
                                    conv_im2col=args.conv_im2col)

    # the last fault-bearing record's per-tile census, schema-checked
    recs = [r for r in sink.records if "fault" in r]
    assert recs, "no fault metrics record emitted"
    last = recs[-1]
    errs = obs_schema.validate_record(last)
    assert not errs, f"per-tile record failed schema: {errs}"
    pt = last["fault"].get("per_tile") or {}
    census = {}
    for k, e in pt.items():
        bf = np.asarray(e["broken_frac"], np.float64)
        census[k] = {
            "grid": (np.asarray(e["grid"]).reshape(-1, 2)[0].tolist()),
            "tiles": int(bf.shape[-1]),
            "broken_frac_mean": round(float(bf.mean()), 4),
            "broken_frac_max": round(float(bf.max()), 4),
        }
        if "view" in e:
            census[k]["im2col_view"] = (
                np.asarray(e["view"]).reshape(-1, 2)[0].tolist())
    broken = runner.broken_fractions()
    setup_rec = runner.setup_record(setup_s)
    n_chips = len(np.asarray(runner.mesh.devices).ravel())
    img_s = N_CONFIGS * BATCH * STEPS / dt
    engine_resolved = runner.engine_resolved
    fused = bool(runner.fused_epilogue_resolved)
    conv_resolved = runner.conv_im2col_resolved
    conv_reason = runner.conv_im2col_reason
    runner.close()

    extra = {
        "input_resolution": "3x224x224",
        "net": args.net,
        "tile_spec": tspec.canonical(),
        "tile_grids": grids,
        "per_tile_census_final": census,
        "broken_fraction_mean": round(float(np.mean(broken)), 4),
        "mesh": dict(runner.mesh.shape),
        "chips": n_chips,
        "n_configs": N_CONFIGS, "batch": BATCH,
        "steps_timed": STEPS, "chunk": CHUNK,
        "seconds": round(dt, 3),
        "setup_seconds": round(setup_s, 1),
        "configs_per_hour_aggregate": round(
            N_CONFIGS * STEPS / dt * 3600.0 / 5000.0, 2),
        "engine": engine_resolved,
        "fused_epilogue": fused,
        "bytes_per_step_est": setup_rec.get("bytes_per_step_est"),
        "backend": jax.default_backend(),
        # the trajectory guard (scripts/check_bench_trajectory.py)
        # reads this to decide cross-revision comparability
        "note": ("CPU-measured (virtual host devices) at reduced "
                 "scale; relative operand-mode comparison only — "
                 "replay on TPU for absolute img/s/chip"
                 if jax.default_backend() == "cpu"
                 else f"{jax.default_backend()}-measured"),
    }
    if views:
        extra["im2col_views"] = views
    if conv_net:
        # ISSUE 19 "measured all three ways": the primary run's
        # resolved operand mode plus one re-traced run per OTHER mode,
        # so the row carries the premat/tilewise/implicit comparison
        # (img/s/chip, bytes_per_step_est HBM floor, and the
        # conv_patch_bytes patch-operand share each mode moves).
        # tilewise on the Pallas engine resolves to premat (recorded),
        # so its column then duplicates the premat one — by design.
        extra["conv_im2col_mode"] = conv_resolved or "premat"
        if conv_reason:
            extra["conv_im2col_reason"] = conv_reason
        extra["conv_patch_bytes"] = setup_rec.get("conv_patch_bytes")
        for mode in ("premat", "tilewise", "implicit"):
            if mode == (conv_resolved or "premat"):
                continue
            solver2, _ = build_solver()
            runner2, setup2_s, dt2 = timed_run(solver2,
                                               conv_im2col=mode)
            rec2 = runner2.setup_record(setup2_s)
            extra[f"img_s_chip_{mode}"] = round(
                N_CONFIGS * BATCH * STEPS / dt2 / n_chips, 2)
            extra[f"seconds_{mode}"] = round(dt2, 3)
            extra[f"bytes_per_step_est_{mode}"] = rec2.get(
                "bytes_per_step_est")
            extra[f"conv_patch_bytes_{mode}"] = rec2.get(
                "conv_patch_bytes")
            extra[f"conv_im2col_resolved_{mode}"] = \
                runner2.conv_im2col_resolved
            runner2.close()

    print(json.dumps({
        "metric": "images/sec/chip, ImageNet-resolution tiled-crossbar "
                  f"fault sweep ({args.net}, {N_CONFIGS} configs "
                  f"config-sharded over {n_chips} chips, "
                  f"tiles={tspec.canonical()})",
        "value": round(img_s / n_chips, 2),
        "unit": "img/s/chip",
        "extra": extra,
    }))


if __name__ == "__main__":
    main()

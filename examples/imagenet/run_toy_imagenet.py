"""The full create_imagenet.sh -> make_imagenet_mean.sh ->
train_caffenet.sh flow (reference examples/imagenet/*.sh) end-to-end on
a GENERATED image-folder dataset, so the pipeline is provable with no
ILSVRC12 download and no imaging dependency:

  1. write class-colored PNGs with the in-repo encoder
     (data/imagecodec.py — no PIL),
  2. convert_imageset (resize + shuffle) -> train LMDB,
  3. compute_image_mean -> mean.binaryproto,
  4. train a small convnet whose TRAIN phase reads the LMDB and whose
     TEST phase reads the raw folder through ImageData — both ingest
     paths in one net — via caffe_cli train.

    python examples/imagenet/run_toy_imagenet.py \
        [--classes 5] [--per-class 24] [--iters 60] [--out DIR]

Prints the final test accuracy; >= 0.5 on 5 classes shows real
signal flow (chance = 0.2).
"""
import argparse
import os
import shutil
import sys
import tempfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..", "..")
sys.path.insert(0, REPO)

TRAIN_VAL = """
name: "ToyImageNet"
layer {{ name: "data" type: "Data" top: "data" top: "label"
  include {{ phase: TRAIN }}
  transform_param {{ mean_file: "{mean}" scale: 0.0078125 }}
  data_param {{ source: "{lmdb}" batch_size: 32 backend: LMDB }} }}
layer {{ name: "data" type: "ImageData" top: "data" top: "label"
  include {{ phase: TEST }}
  transform_param {{ mean_file: "{mean}" scale: 0.0078125 }}
  image_data_param {{ source: "{val_list}" root_folder: "{root}/"
    batch_size: {val_batch} new_height: {size} new_width: {size} }} }}
layer {{ name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param {{ num_output: 16 kernel_size: 5 stride: 2
    weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }}
layer {{ name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param {{ pool: MAX kernel_size: 3 stride: 2 }} }}
layer {{ name: "fc1" type: "InnerProduct" bottom: "pool1" top: "fc1"
  inner_product_param {{ num_output: 32
    weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "relu2" type: "ReLU" bottom: "fc1" top: "fc1" }}
layer {{ name: "fc2" type: "InnerProduct" bottom: "fc1" top: "fc2"
  inner_product_param {{ num_output: {classes}
    weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "fc2"
  bottom: "label" top: "loss" }}
layer {{ name: "accuracy" type: "Accuracy" bottom: "fc2" bottom: "label"
  top: "accuracy" include {{ phase: TEST }} }}
"""


def make_dataset(root, classes, per_class, size, seed=0):
    """Class-colored noise PNGs + train/val list files (80/20)."""
    from rram_caffe_simulation_tpu.data import imagecodec
    rng = np.random.RandomState(seed)
    entries = []
    for c in range(classes):
        base = np.zeros(3)
        base[c % 3] = 200
        base[(c // 3) % 3] += 55 * (1 + c // 9)
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = np.clip(base[None, None]
                          + rng.randn(size, size, 3) * 40, 0,
                          255).astype(np.uint8)
            rel = f"class{c}/img{i}.png"
            with open(os.path.join(root, rel), "wb") as f:
                f.write(imagecodec.encode_png(img))
            entries.append((rel, c))
    rng.shuffle(entries)
    n_val = max(len(entries) // 5, 1)
    val, train = entries[:n_val], entries[n_val:]
    for name, part in (("train.txt", train), ("val.txt", val)):
        with open(os.path.join(root, name), "w") as f:
            f.writelines(f"{rel} {c}\n" for rel, c in part)
    return len(train), len(val)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--classes", type=int, default=5)
    p.add_argument("--per-class", type=int, default=24)
    p.add_argument("--size", type=int, default=40,
                   help="generated image size (resized to 32 for the db)")
    p.add_argument("--iters", type=int, default=60)
    p.add_argument("--out", default="",
                   help="workdir (default: a temp dir, removed after)")
    args = p.parse_args(argv)

    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.tools import converters
    from rram_caffe_simulation_tpu.tools import caffe_cli
    from rram_caffe_simulation_tpu.utils import io as uio

    work = args.out or tempfile.mkdtemp(prefix="toy_imagenet_")
    os.makedirs(work, exist_ok=True)
    root = os.path.join(work, "images")
    n_train, n_val = make_dataset(root, args.classes, args.per_class,
                                  args.size)
    print(f"dataset: {n_train} train / {n_val} val images, "
          f"{args.classes} classes", flush=True)

    lmdb = os.path.join(work, "toy_train_lmdb")      # create_imagenet.sh
    converters.convert_imageset(root, os.path.join(root, "train.txt"),
                                lmdb, resize_height=32, resize_width=32,
                                shuffle=True)
    mean = os.path.join(work, "mean.binaryproto")    # make_imagenet_mean
    _, n = converters.compute_image_mean(lmdb, mean)
    assert n == n_train

    netp = pb.NetParameter()
    from google.protobuf import text_format
    text_format.Parse(TRAIN_VAL.format(
        mean=mean, lmdb=lmdb, val_list=os.path.join(root, "val.txt"),
        root=root, val_batch=n_val, size=32, classes=args.classes), netp)
    net_path = os.path.join(work, "train_val.prototxt")
    uio.write_proto_text(net_path, netp)

    sp = pb.SolverParameter()
    sp.net = net_path
    sp.base_lr = 0.01
    sp.momentum = 0.9
    sp.lr_policy = "fixed"
    sp.type = "SGD"
    sp.max_iter = args.iters
    sp.display = max(args.iters // 3, 1)
    sp.test_interval = args.iters             # test once, at the end
    sp.test_iter.append(1)
    sp.random_seed = 7
    sp.snapshot_prefix = os.path.join(work, "toy")
    solver_path = os.path.join(work, "solver.prototxt")
    uio.write_proto_text(solver_path, sp)

    rc = caffe_cli.main(["train", "--solver", solver_path])  # train_caffenet
    assert rc == 0

    # re-score through the Solver API to return the number
    from rram_caffe_simulation_tpu.solver import Solver
    s = Solver(solver_path)
    s.params = s.net.copy_trained_from(
        s.params, os.path.join(work, f"toy_iter_{args.iters}.caffemodel"))
    acc = s.test(0)["accuracy"]
    print(f"final val accuracy: {float(np.ravel(acc)[0]):.3f} "
          f"(chance {1 / args.classes:.3f})", flush=True)
    if not args.out:
        shutil.rmtree(work, ignore_errors=True)
    return float(np.ravel(acc)[0])


if __name__ == "__main__":
    main()

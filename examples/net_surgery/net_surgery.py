#!/usr/bin/env python
"""Net surgery example (reference examples/net_surgery.ipynb): editing
model parameters in place through the pycaffe-style API, and casting a
classifier's inner-product layers into convolutions for dense,
fully-convolutional inference.

Part 1 — designer filters: a one-conv net's randomly initialized filters
are overwritten with a Gaussian blur and a Sobel edge detector; the
blurred response loses high-frequency energy, the Sobel response picks up
the vertical edge.

Part 2 — the full-conv cast (reference bvlc_caffenet_full_conv.prototxt):
CaffeNet's fc6/fc7/fc8 become fc6-conv (6x6)/fc7-conv (1x1)/fc8-conv
(1x1); the fc weights transplant by flat reshape (innerproduct and
convolution weights have identical memory layout over the same receptive
field). At the original 227x227 input the conv-cast net reproduces the
classifier's probabilities EXACTLY (pinned to 1e-5); at 451x451 it emits
an 8x8 map of class scores in one forward.

    python examples/net_surgery/net_surgery.py
"""
import os
import sys

import numpy as np
from google.protobuf import text_format

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "models"))

from rram_caffe_simulation_tpu import api  # noqa: E402
from rram_caffe_simulation_tpu.proto import pb  # noqa: E402

CONV_NET = """
name: "ConvSurgery"
layer { name: "data" type: "Input" top: "data"
  input_param { shape { dim: 1 dim: 1 dim: 32 dim: 32 } } }
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
  convolution_param { num_output: 2 kernel_size: 5 pad: 2
    weight_filler { type: "gaussian" std: 0.01 } } }
"""


def gaussian_kernel(size=5, sigma=1.5):
    ax = np.arange(size) - size // 2
    g = np.exp(-(ax[:, None] ** 2 + ax[None, :] ** 2) / (2 * sigma ** 2))
    return g / g.sum()


def designer_filters():
    """Part 1: overwrite filters in net.params and observe the responses."""
    npar = pb.NetParameter()
    text_format.Parse(CONV_NET, npar)
    net = api.Net(npar, pb.TEST)

    # an image with a vertical edge + noise
    rng = np.random.RandomState(0)
    im = np.zeros((1, 1, 32, 32), np.float32)
    im[..., 16:] = 1.0
    im += rng.randn(*im.shape).astype(np.float32) * 0.1

    # surgery: filter 0 = Gaussian blur, filter 1 = Sobel x
    net.params["conv"][0].data[0, 0] = gaussian_kernel()
    sobel = np.zeros((5, 5), np.float32)
    sobel[1:4, 1:4] = [[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]]
    net.params["conv"][0].data[1, 0] = sobel
    net.params["conv"][1].data[:] = 0

    out = net.forward(data=im)["conv"]
    blur, edge = out[0, 0], out[0, 1]
    hf = lambda a: np.abs(np.diff(a, axis=1)).mean()  # noqa: E731
    print(f"high-frequency energy: input {hf(im[0, 0]):.4f} "
          f"-> blurred {hf(blur):.4f}")
    assert hf(blur) < hf(im[0, 0]) * 0.6, "blur must suppress noise"
    edge_col = np.abs(edge[:, 14:18]).mean()
    flat_col = np.abs(edge[:, 4:8]).mean()
    print(f"sobel response: edge band {edge_col:.3f} vs flat {flat_col:.3f}")
    assert edge_col > 5 * flat_col, "sobel must localize the edge"


def full_conv_proto():
    """bvlc_caffenet_full_conv: the CaffeNet trunk with conv fc layers,
    451x451 input (generated, like the zoo prototxts)."""
    from zoo_common import WEIGHT_PARAM, caffenet_trunk
    from rram_caffe_simulation_tpu.api.net_spec import NetSpec, layers as L

    n = NetSpec()
    n.data = L.Input(input_param=dict(shape=dict(dim=[1, 3, 451, 451])))
    caffenet_trunk(n, n.data)
    proto = n.to_proto()
    proto.name = "CaffeNetConv"
    # drop fc6..drop7; rebuild as convolutions
    keep = [lp for lp in proto.layer
            if not (lp.name.startswith(("fc", "relu6", "relu7", "drop")))]
    del proto.layer[:]
    proto.layer.extend(keep)

    m = NetSpec()
    # a scaffold Input named pool5 grafts the head onto the trunk's last
    # blob; the Input layer itself is dropped below
    m.pool5 = L.Input(input_param=dict(shape=dict(dim=[1, 256, 6, 6])))
    m["fc6-conv"] = L.Convolution(
        m.pool5, num_output=4096, kernel_size=6, param=WEIGHT_PARAM)
    m["relu6"] = L.ReLU(m["fc6-conv"], in_place=True)
    m["fc7-conv"] = L.Convolution(
        m["fc6-conv"], num_output=4096, kernel_size=1, param=WEIGHT_PARAM)
    m["relu7"] = L.ReLU(m["fc7-conv"], in_place=True)
    m["fc8-conv"] = L.Convolution(
        m["fc7-conv"], num_output=1000, kernel_size=1, param=WEIGHT_PARAM)
    m.prob = L.Softmax(m["fc8-conv"])
    head = m.to_proto()
    proto.layer.extend(lp for lp in head.layer if lp.type != "Input")
    return proto


def transplant(dst, src):
    """fc -> conv weight transplant: identical flat layout, reshaped."""
    for conv_name, fc_name in (("fc6-conv", "fc6"), ("fc7-conv", "fc7"),
                               ("fc8-conv", "fc8")):
        for i in (0, 1):
            dst.params[conv_name][i].data[:] = (
                src.params[fc_name][i].data.reshape(
                    dst.params[conv_name][i].data.shape))


def full_conv_cast():
    """Part 2: conv-cast CaffeNet == the classifier at 227, dense at 451."""
    fc_net = api.Net(os.path.join(ROOT, "models", "bvlc_reference_caffenet",
                                  "deploy.prototxt"), pb.TEST)
    proto = full_conv_proto()
    with open(os.path.join(HERE, "bvlc_caffenet_full_conv.prototxt"),
              "w") as f:
        f.write(str(proto))

    # numeric-contract check at 227: the conv net must reproduce the
    # classifier's probabilities bit-for-near-bit
    for shape in proto.layer[0].input_param.shape:
        shape.dim[2] = shape.dim[3] = 227
    conv_net = api.Net(proto, pb.TEST)
    # trunk weights share names; heads transplant by reshape
    for lname in ("conv1", "conv2", "conv3", "conv4", "conv5"):
        for i in (0, 1):
            conv_net.params[lname][i].data[:] = fc_net.params[lname][i].data
    transplant(conv_net, fc_net)

    rng = np.random.RandomState(1)
    im = rng.rand(1, 3, 227, 227).astype(np.float32) * 255
    probs_fc = fc_net.forward(data=im[:1])["prob"]
    probs_conv = conv_net.forward(data=im)["prob"]
    np.testing.assert_allclose(probs_conv[0, :, 0, 0], probs_fc[0],
                               atol=1e-5)
    print("227x227: conv-cast probabilities match the classifier (1e-5)")

    # dense inference at 451: one forward -> a map of class scores
    proto451 = full_conv_proto()
    conv451 = api.Net(proto451, pb.TEST)
    for lname in ("conv1", "conv2", "conv3", "conv4", "conv5"):
        for i in (0, 1):
            conv451.params[lname][i].data[:] = fc_net.params[lname][i].data
    transplant(conv451, fc_net)
    im451 = rng.rand(1, 3, 451, 451).astype(np.float32) * 255
    out = conv451.forward(data=im451)["prob"]
    print(f"451x451: dense class-probability map {out.shape[2]}x"
          f"{out.shape[3]} in one forward")
    assert out.shape[1] == 1000 and out.shape[2] >= 8


def main():
    designer_filters()
    full_conv_cast()
    print("net surgery OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

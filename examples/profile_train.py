"""Per-HLO device-time profile of one fused train step on real TPU.

Captures a jax.profiler trace around Solver.step_fused on a zoo
train_val graph (Data swapped for a device-resident Input feed by
default, or DummyData with --dummy-data) and aggregates the device
events: time by HLO category, top ops by total device time with
achieved FLOP/s and HBM bandwidth. This is the profile-backed MFU
attribution the RESULTS.md table rows point at.

    python examples/profile_train.py \
        --model models/bvlc_googlenet/train_val.prototxt \
        --batch 128 --compute-dtype bfloat16
"""
import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.join(HERE, "..")
sys.path.insert(0, REPO)

from bench_train import dummyize, inputize, fixed_feed  # noqa: E402


def capture(args):
    os.chdir(REPO)
    import jax
    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.utils.io import read_net_param

    netp = read_net_param(args.model)
    if args.dummy_data:
        netp = dummyize(netp, args.batch)
        feed = None
    else:
        # default: Input layers + a pre-staged host batch — the profiled
        # step then contains no in-graph input generation (the DummyData
        # RNG ops claimed 6-15% of the r4 attributions)
        netp, spec = inputize(netp, args.batch)
        feed = fixed_feed(spec)
    sp = pb.SolverParameter()
    sp.net_param.CopyFrom(netp)
    sp.base_lr = 0.001
    sp.momentum = 0.9
    sp.weight_decay = 0.0005
    sp.lr_policy = "fixed"
    sp.type = "SGD"
    sp.max_iter = 10 ** 9
    sp.display = 0
    sp.random_seed = 7
    solver = Solver(sp, train_feed=feed,
                    compute_dtype=args.compute_dtype or None)
    # compile + warmup outside the trace. --no-scan profiles the plain
    # per-iteration step: the fused path wraps the same body in a scan
    # `while`, which the trace reports as one opaque event.
    step = ((lambda n: solver.step(n)) if args.no_scan
            else (lambda n: solver.step_fused(n, chunk=n)))
    step(args.chunk)
    jax.block_until_ready(jax.tree.leaves(solver.params))
    tracedir = tempfile.mkdtemp(prefix="train_profile_")
    with jax.profiler.trace(tracedir):
        step(args.chunk)
        jax.block_until_ready(jax.tree.leaves(solver.params))
    files = sorted(glob.glob(
        os.path.join(tracedir, "plugins/profile/*/*.trace.json.gz")))
    assert files, f"no trace under {tracedir}"
    return files[-1], args.chunk


def device_events(trace_file):
    t = json.load(gzip.open(trace_file))
    ev = t["traceEvents"]
    tpu_pids = {e["pid"] for e in ev
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "TPU" in e["args"].get("name", "")}
    for e in ev:
        if e.get("ph") == "X" and e.get("pid") in tpu_pids \
                and "hlo_category" in e.get("args", {}):
            yield e


def aggregate(trace_file, n_iters, peak_tflops, top=25):
    by_cat = collections.Counter()
    by_op = {}
    total = 0.0
    for e in device_events(trace_file):
        a = e["args"]
        dur = e["dur"]  # us
        cat = a["hlo_category"]
        by_cat[cat] += dur
        total += dur
        # merge by (base name, category, source op) so distinct fusions
        # with the generic "fusion.N" name stay distinguishable
        base = e["name"].rstrip("0123456789").rstrip(".")
        key = (base, cat, a.get("tf_op", "")[:60])
        rec = by_op.setdefault(key, dict(
            dur=0.0, n=0, flops=0, bytes=0, cat=cat,
            tf_op=a.get("tf_op", ""), long=a.get("long_name", "")[:200]))
        rec["dur"] += dur
        rec["n"] += 1
        rec["flops"] += int(a.get("model_flops", 0) or 0)
        rec["bytes"] += int(a.get("raw_bytes_accessed", 0) or 0)

    print(f"device total: {total / 1e3:.2f} ms over {n_iters} iters "
          f"({total / 1e3 / n_iters:.2f} ms/iter)")
    print("\n-- time by HLO category --")
    for cat, dur in by_cat.most_common():
        print(f"  {cat:<28} {dur / 1e3:9.2f} ms  {100 * dur / total:5.1f}%")
    print(f"\n-- top {top} ops by device time --")
    print(f"  {'op / source':<58}{'ms':>8}{'%':>6}{'TFLOP/s':>9}"
          f"{'GB/s':>7}  kind")
    for key, r in sorted(by_op.items(), key=lambda kv: -kv[1]["dur"])[:top]:
        tflops = r["flops"] / (r["dur"] * 1e-6) / 1e12 if r["dur"] else 0
        gbs = r["bytes"] / (r["dur"] * 1e-6) / 1e9 if r["dur"] else 0
        label = (r["tf_op"].split("/")[-1].rstrip(":") or key[0])[:58]
        print(f"  {label:<58}{r['dur'] / 1e3:8.2f}"
              f"{100 * r['dur'] / total:6.1f}{tflops:9.2f}{gbs:7.0f}"
              f"  {r['cat']}")
    mxu = sum(d for c, d in by_cat.items() if "convolution" in c)
    print(f"\nconvolution-category time: {100 * mxu / total:.1f}% of device"
          f" — everything else is MXU-idle overhead")
    flops = sum(r["flops"] for r in by_op.values())
    ach = flops / (total * 1e-6) / 1e12 if total else 0.0
    print(f"achieved over the whole capture: {ach:.1f} TFLOP/s = "
          f"{100 * ach / peak_tflops:.1f}% of the {peak_tflops:.0f} TF peak")
    return by_cat, by_op, total


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", required=True)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--chunk", type=int, default=5)
    p.add_argument("--compute-dtype", default="bfloat16")
    p.add_argument("--peak-tflops", type=float, default=197.0)
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--no-scan", action="store_true",
                   help="profile Solver.step instead of step_fused "
                        "(breaks the scan `while` out into its body ops)")
    p.add_argument("--dummy-data", action="store_true",
                   help="generate inputs in-graph via DummyData (the r4 "
                        "harness); default is a device-resident Input "
                        "feed with no in-step generation")
    p.add_argument("--trace", default="",
                   help="parse an existing trace.json.gz instead of "
                        "capturing")
    args = p.parse_args(argv)
    if args.trace:
        trace_file, n = args.trace, args.chunk
    else:
        trace_file, n = capture(args)
        print(f"trace: {trace_file}")
    aggregate(trace_file, n, args.peak_tflops, args.top)


if __name__ == "__main__":
    main()

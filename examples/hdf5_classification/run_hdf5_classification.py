#!/usr/bin/env python
"""HDF5 classification example (reference examples/hdf5_classification):
non-image tabular data through the HDF5Data layer.

Generates a 4-feature 2-class dataset (two informative features + two
noise features, matching the reference notebook's sklearn make_
classification operating point), writes HDF5 train/test shards + source
list files, then trains and evaluates BOTH nets of the reference example:

- logreg: data -> fc(2) -> softmax (linear decision boundary, ~74%)
- nonlinear: data -> fc(40) -> ReLU -> fc(2) (~84%)

Everything runs through the product path: HDF5Data feed with per-epoch
reshuffle -> jitted Solver -> TEST-phase Accuracy.

    python examples/hdf5_classification/run_hdf5_classification.py
"""
import os
import sys

import h5py
import numpy as np
from google.protobuf import text_format

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, ROOT)

from rram_caffe_simulation_tpu.proto import pb  # noqa: E402
from rram_caffe_simulation_tpu.solver import Solver  # noqa: E402


def make_dataset(seed=0, n=10000):
    """2 informative features + 2 pure-noise features, with TWO gaussian
    clusters per class (like make_classification's default): each class
    has a majority cluster a linear boundary can separate (~73%) and a
    minority cluster on the wrong side of it that only a nonlinear model
    recovers — reproducing the reference notebook's accuracy gap."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 2, size=n)
    minority = rng.rand(n) < 0.2
    centers = np.array([
        [[-1.2, -1.2], [2.2, 2.2]],     # class 0: majority, minority
        [[1.2, 1.2], [-2.2, -2.2]],     # class 1: majority, minority
    ])
    informative = (centers[y, minority.astype(int)] +
                   rng.randn(n, 2) * 0.8)
    noise = rng.randn(n, 2) * 1.5
    X = np.concatenate([informative, noise], axis=1).astype(np.float32)
    X = (X - X.mean(0)) / X.std(0)
    return X, y.astype(np.float32)


def write_hdf5(data_dir, X, y, split=7500):
    os.makedirs(data_dir, exist_ok=True)
    for name, sl in (("train", slice(None, split)),
                     ("test", slice(split, None))):
        path = os.path.join(data_dir, name + ".h5")
        with h5py.File(path, "w") as f:
            f.create_dataset("data", data=X[sl])
            f.create_dataset("label", data=y[sl])
        with open(os.path.join(data_dir, name + ".txt"), "w") as f:
            f.write(path + "\n")


def net_text(name, hidden, data_dir):
    """The reference train_val nets, parameterized by the hidden width
    (0 = plain logistic regression)."""
    fc = (f"""
layer {{ name: "fc1" type: "InnerProduct" bottom: "data" top: "fc1"
  param {{ lr_mult: 1 decay_mult: 1 }} param {{ lr_mult: 2 decay_mult: 0 }}
  inner_product_param {{ num_output: {hidden}
    weight_filler {{ type: "xavier" }}
    bias_filler {{ type: "constant" value: 0 }} }} }}
layer {{ name: "relu1" type: "ReLU" bottom: "fc1" top: "fc1" }}
layer {{ name: "fc2" type: "InnerProduct" bottom: "fc1" top: "fc2"
  param {{ lr_mult: 1 decay_mult: 1 }} param {{ lr_mult: 2 decay_mult: 0 }}
  inner_product_param {{ num_output: 2
    weight_filler {{ type: "xavier" }}
    bias_filler {{ type: "constant" value: 0 }} }} }}
""" if hidden else """
layer { name: "fc1" type: "InnerProduct" bottom: "data" top: "fc2"
  param { lr_mult: 1 decay_mult: 1 } param { lr_mult: 2 decay_mult: 0 }
  inner_product_param { num_output: 2
    weight_filler { type: "xavier" }
    bias_filler { type: "constant" value: 0 } } }
""")
    return f"""
name: "{name}"
layer {{ name: "data" type: "HDF5Data" top: "data" top: "label"
  include {{ phase: TRAIN }}
  hdf5_data_param {{ source: "{data_dir}/train.txt" batch_size: 10 }} }}
layer {{ name: "data" type: "HDF5Data" top: "data" top: "label"
  include {{ phase: TEST }}
  hdf5_data_param {{ source: "{data_dir}/test.txt" batch_size: 10 }} }}
{fc}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "fc2" bottom: "label"
  top: "loss" }}
layer {{ name: "accuracy" type: "Accuracy" bottom: "fc2" bottom: "label"
  top: "accuracy" include {{ phase: TEST }} }}
"""


def solve(name, hidden, data_dir, max_iter=3000):
    sp = pb.SolverParameter()
    text_format.Parse(net_text(name, hidden, data_dir), sp.net_param)
    sp.test_iter.append(250)
    sp.test_interval = max_iter  # evaluate at the end (and at iter 0)
    sp.base_lr = 0.01
    sp.lr_policy = "step"
    sp.gamma = 0.1
    sp.stepsize = 5000
    sp.momentum = 0.9
    sp.weight_decay = 0.0005
    sp.display = max_iter // 4
    sp.max_iter = max_iter
    sp.random_seed = 1
    sp.snapshot_prefix = os.path.join(data_dir, name)
    solver = Solver(sp)
    solver.solve()
    scores = solver.test()
    acc = float(np.mean(scores["accuracy"]))
    print(f"{name}: test accuracy = {acc:.4f}")
    return acc


def main():
    data_dir = os.path.join(HERE, "data")
    X, y = make_dataset()
    write_hdf5(data_dir, X, y)
    acc_logreg = solve("LogisticRegressionNet", 0, data_dir)
    acc_nonlinear = solve("NonlinearNet", 40, data_dir)
    assert acc_nonlinear > acc_logreg, (
        "the ReLU net should beat the linear model on this task")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

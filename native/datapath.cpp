// Native data path: LMDB page walk + Datum protobuf decode + transform.
//
// The reference's input pipeline is native C++ (util/db_lmdb.cpp over
// liblmdb, Datum decode via C++ protobuf, data_transformer.cpp); this is
// the TPU framework's native equivalent, exposed over a plain C ABI and
// loaded via ctypes (pybind11 is not available in the build image). The
// Python reader (data/lmdb_py.py) stays as the portable fallback and the
// writer; this library accelerates the hot read+decode+transform path.
//
// LMDB 0.9 on-disk layout implemented here (struct layout per lmdb's
// public docs, mirroring data/lmdb_py.py):
//   page header 16B: pgno u64 | pad u16 | flags u16 | lower u16 | upper u16
//   node header 8B:  lo u16 | hi u16 | flags u16 | ksize u16
//     leaf:   datasize = lo | hi<<16; F_BIGDATA(0x01) -> overflow pgno u64
//     branch: child pgno = lo | hi<<16 | flags<<32
//   meta at +16 on pages 0/1: magic u32 | version u32 | addr u64 |
//     mapsize u64 | free_db[48] | main_db[48] | last_pg u64 | txnid u64
//   db record 48B: pad u32 | flags u16 | depth u16 | branch u64 | leaf u64 |
//     overflow u64 | entries u64 | root u64
//
// Datum wire format (proto/caffe.proto message Datum):
//   1: channels varint   2: height varint   3: width varint
//   4: data bytes        5: label varint    6: float_data (packed/repeated)
//   7: encoded varint
//
// Transform semantics (data_transformer.cpp:19-150 order): subtract
// full-size mean (blob or per-channel value), center-crop (TEST), scale.
// Random TRAIN crop/mirror stay on the Python path.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr size_t kPage = 4096;
constexpr uint32_t kMagic = 0xBEEFC0DE;
constexpr uint32_t kVersion = 1;
constexpr uint16_t kPBranch = 0x01;
constexpr uint16_t kPLeaf = 0x02;
constexpr uint16_t kPMeta = 0x08;
constexpr uint16_t kFBigData = 0x01;
constexpr uint64_t kInvalid = ~0ULL;

inline uint16_t rd16(const uint8_t* p) { uint16_t v; std::memcpy(&v, p, 2); return v; }
inline uint32_t rd32(const uint8_t* p) { uint32_t v; std::memcpy(&v, p, 4); return v; }
inline uint64_t rd64(const uint8_t* p) { uint64_t v; std::memcpy(&v, p, 8); return v; }

struct Record { uint64_t off; uint64_t len; };

struct Env {
  int fd = -1;
  const uint8_t* mm = nullptr;
  size_t size = 0;
  std::vector<Record> records;   // in key order
  std::string error;
};

thread_local std::string g_error;

bool walk(Env* e, uint64_t root) {
  if (root == kInvalid) return true;   // empty DB
  std::vector<std::pair<uint64_t, uint32_t>> stack{{root, 0}};
  while (!stack.empty()) {
    auto [pgno, idx] = stack.back();
    stack.pop_back();
    if ((pgno + 1) * kPage > e->size) { e->error = "page out of range"; return false; }
    const uint8_t* pg = e->mm + pgno * kPage;
    uint16_t flags = rd16(pg + 10), lower = rd16(pg + 12);
    uint32_t n = (lower - 16) / 2;
    if (flags & kPLeaf) {
      for (uint32_t i = 0; i < n; ++i) {
        uint16_t ptr = rd16(pg + 16 + 2 * i);
        const uint8_t* node = pg + ptr;
        uint16_t lo = rd16(node), hi = rd16(node + 2),
                 nflags = rd16(node + 4), ksize = rd16(node + 6);
        uint64_t datasize = uint64_t(lo) | (uint64_t(hi) << 16);
        if (nflags & kFBigData) {
          uint64_t ovf = rd64(node + 8 + ksize);
          e->records.push_back({ovf * kPage + 16, datasize});
        } else {
          e->records.push_back({uint64_t(node - e->mm) + 8 + ksize, datasize});
        }
      }
    } else if (flags & kPBranch) {
      if (idx < n) {
        stack.push_back({pgno, idx + 1});
        uint16_t ptr = rd16(pg + 16 + 2 * idx);
        const uint8_t* node = pg + ptr;
        uint64_t child = uint64_t(rd16(node)) | (uint64_t(rd16(node + 2)) << 16) |
                         (uint64_t(rd16(node + 4)) << 32);
        stack.push_back({child, 0});
      }
    } else {
      e->error = "unexpected page flags";
      return false;
    }
  }
  return true;
}

// --- Datum decode ---------------------------------------------------------

struct Datum {
  int64_t channels = 0, height = 0, width = 0, label = 0, encoded = 0;
  const uint8_t* data = nullptr;
  uint64_t data_len = 0;
  const uint8_t* float_data = nullptr;   // packed floats
  uint64_t float_count = 0;
};

inline bool varint(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    v |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) { *out = v; return true; }
    shift += 7;
  }
  return false;
}

bool decode_datum(const uint8_t* p, uint64_t len, Datum* d) {
  const uint8_t* end = p + len;
  while (p < end) {
    uint64_t tag;
    if (!varint(p, end, &tag)) return false;
    uint32_t field = uint32_t(tag >> 3), wire = uint32_t(tag & 7);
    uint64_t v;
    switch (wire) {
      case 0:  // varint
        if (!varint(p, end, &v)) return false;
        if (field == 1) d->channels = int64_t(v);
        else if (field == 2) d->height = int64_t(v);
        else if (field == 3) d->width = int64_t(v);
        else if (field == 5) d->label = int64_t(v);
        else if (field == 7) d->encoded = int64_t(v);
        break;
      case 2:  // length-delimited
        if (!varint(p, end, &v) || p + v > end) return false;
        if (field == 4) { d->data = p; d->data_len = v; }
        else if (field == 6) { d->float_data = p; d->float_count = v / 4; }
        p += v;
        break;
      case 5:  // fixed32 (non-packed repeated float_data)
        if (p + 4 > end) return false;
        if (field == 6 && d->float_data == nullptr) d->float_data = p;
        if (field == 6) d->float_count += 1;
        p += 4;
        break;
      case 1:  // fixed64
        if (p + 8 > end) return false;
        p += 8;
        break;
      default:
        return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

const char* dp_last_error() { return g_error.c_str(); }

void* dp_open(const char* path) {
  std::string p(path);
  struct stat st;
  if (stat(p.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) p += "/data.mdb";
  int fd = open(p.c_str(), O_RDONLY);
  if (fd < 0) { g_error = "cannot open " + p; return nullptr; }
  if (fstat(fd, &st) != 0) { close(fd); g_error = "fstat failed"; return nullptr; }
  void* mm = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (mm == MAP_FAILED) { close(fd); g_error = "mmap failed"; return nullptr; }
  auto* e = new Env{fd, static_cast<const uint8_t*>(mm),
                    size_t(st.st_size), {}, ""};
  // pick the newer meta page, mirroring lmdb_py.Environment
  uint64_t best_txn = 0, root = kInvalid, entries = 0;
  bool ok = false;
  for (int m = 0; m < 2; ++m) {
    const uint8_t* pg = e->mm + m * kPage;
    if (!(rd16(pg + 10) & kPMeta)) continue;
    if (rd32(pg + 16) != kMagic || rd32(pg + 20) != kVersion) continue;
    const uint8_t* main_db = pg + 16 + 24 + 48;
    uint64_t ent = rd64(main_db + 32), rt = rd64(main_db + 40);
    uint64_t txn = rd64(main_db + 48 + 8);
    // ties prefer meta page 0, like lmdb_py (m0 if m0.txnid >= m1.txnid)
    if (!ok || txn > best_txn) { best_txn = txn; root = rt; entries = ent; }
    ok = true;
  }
  if (!ok) { g_error = "no valid LMDB meta page"; delete e; return nullptr; }
  e->records.reserve(entries);
  if (!walk(e, root)) { g_error = e->error; delete e; return nullptr; }
  return e;
}

void dp_close(void* env) {
  auto* e = static_cast<Env*>(env);
  if (!e) return;
  munmap(const_cast<uint8_t*>(e->mm), e->size);
  close(e->fd);
  delete e;
}

long dp_count(void* env) {
  return long(static_cast<Env*>(env)->records.size());
}

// Shape of record 0: dims_out = {channels, height, width}; returns 0 on
// success, -1 on error (empty DB / undecodable / encoded image).
long dp_shape(void* env, long* dims_out) {
  auto* e = static_cast<Env*>(env);
  if (e->records.empty()) { g_error = "empty DB"; return -1; }
  Datum d;
  if (!decode_datum(e->mm + e->records[0].off, e->records[0].len, &d)) {
    g_error = "cannot decode first Datum";
    return -1;
  }
  if (d.encoded) { g_error = "encoded (JPEG) Datums need the Python path"; return -1; }
  dims_out[0] = d.channels; dims_out[1] = d.height; dims_out[2] = d.width;
  return 0;
}

// Decode `n` records starting at index `start` (wrapping) into out
// (n, c, h', w') float32 and out_labels (n) float32, applying
// (x - mean) then center-crop `crop` (0 = none) then * scale.
// dims = {c, h, w} the caller sized `out` for (from dp_shape); EVERY
// record must match or the call fails — never trusts record contents to
// bound the write.
// mean_mode: 0 none, 1 per-channel (mean has c floats),
//            2 full blob (c*h*w floats, indexed pre-crop).
// Returns 0 on success, -1 on error (g_error says why).
long dp_read_batch(void* env, long start, long n, long crop,
                   const long* dims,
                   const float* mean, int mean_mode, float scale,
                   float* out, float* out_labels) {
  auto* e = static_cast<Env*>(env);
  const long total = long(e->records.size());
  if (total == 0) { g_error = "empty DB"; return -1; }
  const long c0 = dims[0], h0 = dims[1], w0 = dims[2];
  if (crop && (crop > h0 || crop > w0)) {
    g_error = "crop larger than record";
    return -1;
  }
  float* dst = out;
  for (long i = 0; i < n; ++i) {
    const Record& r = e->records[(start + i) % total];
    Datum d;
    if (!decode_datum(e->mm + r.off, r.len, &d)) {
      g_error = "cannot decode Datum";
      return -1;
    }
    if (d.encoded) { g_error = "encoded Datum"; return -1; }
    if (d.channels != c0 || d.height != h0 || d.width != w0) {
      g_error = "record shape differs from the expected dims";
      return -1;
    }
    const long hw = h0 * w0;
    const long oh = crop ? crop : h0, ow = crop ? crop : w0;
    const long hoff = crop ? (h0 - crop) / 2 : 0;
    const long woff = crop ? (w0 - crop) / 2 : 0;
    const bool from_bytes = d.data_len > 0;
    if (from_bytes && d.data_len != uint64_t(c0 * hw)) {
      g_error = "data size mismatch";
      return -1;
    }
    if (!from_bytes && d.float_count != uint64_t(c0 * hw)) {
      g_error = "float_data size mismatch";
      return -1;
    }
    for (long ch = 0; ch < c0; ++ch) {
      const float mv = (mean_mode == 1) ? mean[ch] : 0.0f;
      for (long y = 0; y < oh; ++y) {
        const long src_row = (ch * h0 + y + hoff) * w0 + woff;
        for (long x = 0; x < ow; ++x) {
          float v;
          if (from_bytes) {
            v = float(d.data[src_row + x]);
          } else {
            std::memcpy(&v, d.float_data + 4 * (src_row + x), 4);
          }
          if (mean_mode == 2) v -= mean[src_row + x];
          else v -= mv;
          *dst++ = v * scale;
        }
      }
    }
    out_labels[i] = float(d.label);
  }
  return 0;
}

}  // extern "C"

"""Headline benchmark: images/sec/chip under RRAM noise (BASELINE.json
metric), measured on CIFAR-10-quick training with the Gaussian fault engine
fused into every step, Monte-Carlo fault-config axis vmapped on-chip.

The input pipeline is the REAL product path: the CIFAR LMDB is decoded
through the pure-Python reader + DataTransformer (mean/scale) and uploaded
once as a device-resident dataset; every training step then gathers its
batch on-device in host-cursor order (SweepRunner preload — the TPU-first
answer to the reference's 3-thread prefetch pipeline). Steps are scanned
CHUNK-at-a-time under one jit so dispatch latency is off the critical path.

Counting: each of the N simultaneously-trained fault configs consumes the
shared batch every step (the reference trains one config per GPU process —
run_different_mean.sh — so per-config images are the comparable unit of
work). vs_baseline divides by the reference's best published training
throughput, 267 img/s (CaffeNet w/ cuDNN on K40,
docs/performance_hardware.md:23-25).

Prints exactly ONE JSON line on stdout.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BASELINE_IMG_S = 267.0  # reference: CaffeNet+cuDNN on K40

BATCH = 100          # matches the fault engine's per-write decrement
CHUNK = int(os.environ.get("BENCH_CHUNK", "20"))
# forward/backward compute dtype. Default bfloat16 — the MXU-native
# mixed precision (f32 masters, f32 updates/momentum, f32 fault state;
# see Solver.make_train_step compute_dtype). Fault dynamics are
# identical to f32 (broken-fraction equal bit-for-bit; per-config loss
# distributions statistically indistinguishable — RESULTS.md) at ~1.6x
# the throughput. BENCH_DTYPE="" reverts to full f32, the reference's
# arithmetic.
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16") or None
# simultaneous configs: the img/s plateau starts ~256 (RESULTS.md sweep
# table) and half-width dtypes leave HBM room for 512 resident configs
# (~+2%, measured r3); 4-byte state at 512 would exceed the 15.75 GB
# budget, so full-precision runs stay at 256.
N_CONFIGS = int(os.environ.get(
    "BENCH_CONFIGS",
    "512" if DTYPE in ("bfloat16", "float16") else "256"))
# timed steps must be a chunk multiple or the trailing partial chunk
# compiles a second jit INSIDE the timed window
STEPS = max(int(os.environ.get("BENCH_STEPS", "100")) // CHUNK, 1) * CHUNK
# async dispatch pipeline depth (SweepRunner pipeline_depth): in-flight
# chunks whose host bookkeeping the consumer thread hides; 0 = fetch
# inline at every chunk boundary (the pre-pipeline baseline)
PIPELINE = int(os.environ.get("BENCH_PIPELINE", "2"))
# --- the HBM-floor attack knobs (ROADMAP item 3 / ISSUE 7) ---
# fault-state layout: "1" packs the per-cell state into int16/uint8
# banks (fault/packed.py — identical fault transitions, ~4x less
# resident fault HBM per config); "" reverts to the f32 reference
# leaves. Safe on every backend — on by default.
PACKED = os.environ.get("BENCH_PACKED", "1") not in ("", "0")
# hardware-aware crossbar engine (ENGINE MATRIX, fault/hw_aware.py):
# "auto" resolves to the config-batched Pallas kernel on the TPU
# backend (per-lane faulty+noisy weights formed in VMEM, never
# round-tripping HBM; composes with BENCH_DTYPE — the kernel computes
# f32 while activations stay half-width) and to the pure-JAX reference
# path elsewhere. "jax" | "pallas" force a side.
ENGINE = os.environ.get("BENCH_ENGINE", "auto")
# quantized sweep compute ("" | "ternary" | "int8"): fault-target
# weight reads through the quantize_ste ADC grid. Opt-in — it changes
# the arithmetic (RESULTS.md "Quantized & packed sweeps" caveats).
DTYPE_POLICY = os.environ.get("BENCH_DTYPE_POLICY", "") or None
# host span tracer (observe/spans.py): armed AFTER warmup so the
# timed windows carry a per-phase attribution (extra.phase_breakdown —
# dispatch / host-blocked / checkpoint / prefetch seconds, the r08+
# rows' where-do-the-microseconds-go split). Host-side microseconds
# per chunk; BENCH_TRACE=0 drops it for a paranoid clean-timing run.
TRACE = os.environ.get("BENCH_TRACE", "1") not in ("", "0")


def main(argv=None):
    p = argparse.ArgumentParser()
    # min-of-N jitter rejection (the bench_train.py pattern): the
    # tunneled dispatch path swings +-35% run to run, so BENCH_r0N.json
    # trajectories track min(window) and keep every window in extra
    p.add_argument("--repeats", type=int,
                   default=int(os.environ.get("BENCH_REPEATS", "1")),
                   help="timed windows; min is reported, per-window "
                        "seconds land in extra.window_seconds")
    # pod-scale row (ISSUE 9): "--mesh config=N" (or BENCH_MESH) runs
    # the sweep config-SHARDED over N local devices as one GSPMD
    # program — the bench row then reports chips=N and the aggregate
    # configs/hour across the mesh. The default (no mesh) row stays the
    # single-chip measurement for trajectory continuity; emit the mesh
    # row as a separate invocation.
    p.add_argument("--mesh", default=os.environ.get("BENCH_MESH", ""),
                   help="mesh spec, e.g. 'config=4' or 'config=all' "
                        "(every visible device); empty = the classic "
                        "single-chip row")
    args = p.parse_args(argv)
    repeats = max(args.repeats, 1)

    import jax

    from rram_caffe_simulation_tpu import cache as rcache
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.parallel import SweepRunner
    from rram_caffe_simulation_tpu.utils.io import read_solver_param

    os.chdir(REPO)
    # cold-start layer (rram_caffe_simulation_tpu/cache.py): with
    # RRAM_TPU_CACHE_DIR set, the XLA compile and the LMDB decode both
    # come from disk on the second and every later run — the `setup`
    # extra below splits the wall clock so BENCH_r0N.json tracks it
    rcache.enable_compilation_cache()
    t_setup = time.perf_counter()
    sp = read_solver_param(os.path.join(
        REPO, "models", "cifar10_quick",
        "cifar10_quick_lmdb_solver.prototxt"))
    sp.max_iter = 10 ** 9
    sp.display = 0
    sp.random_seed = 1
    sp.snapshot_prefix = "/tmp/bench"
    # reference RRAM operating point (usage.md; solvers/
    # cifar10_vgg11_template.prototxt:36-39): lifetimes ~ N(1e8, 3e7)
    sp.failure_pattern.type = "gaussian"
    sp.failure_pattern.mean = 1e8
    sp.failure_pattern.std = 3e7

    solver = Solver(sp)
    # resolve the "auto" engine HERE (SweepRunner's own "auto" is the
    # conservative jax alias — sweeps opt in to pallas explicitly): the
    # config-batched kernel needs the TPU pallas lowering (interpret
    # mode elsewhere is a debug path). It composes with the bfloat16
    # compute dtype (the kernel computes f32 behind call-site casts;
    # activations keep the half-width HBM traffic). Whether the fused
    # kernel actually ENGAGES (rram_forward.sigma > 0 or an ADC-grid
    # policy — the stock bench point runs sigma == 0) is resolved by
    # make_train_step's use_pallas gate and read back below as
    # runner.engine_resolved; extra.engine always names the engine that
    # actually RAN, never an inert flag — the r06+ HBM-floor
    # attribution depends on it.
    engine = ENGINE
    if engine == "auto":
        engine = "pallas" if jax.default_backend() == "tpu" else "jax"
    # pod-scale path: lay the config axis over the requested mesh (the
    # N-chip GSPMD program; make_mesh's sorted device order). The
    # fused pallas kernel is single-process/config-only — a mesh spec
    # keeps whatever engine resolves, the runner validates the combo.
    mesh = None
    if args.mesh:
        from rram_caffe_simulation_tpu.parallel import mesh_from_spec
        mesh = mesh_from_spec(args.mesh)
    # precompile_chunk: AOT-compile the CHUNK-step function on the main
    # thread while the LMDB decode runs on a background thread — the
    # two cold-start halves overlap instead of serializing
    runner = SweepRunner(solver, n_configs=N_CONFIGS, compute_dtype=DTYPE,
                         precompile_chunk=CHUNK, pipeline_depth=PIPELINE,
                         engine=engine, packed_state=PACKED,
                         dtype_policy=DTYPE_POLICY, mesh=mesh)
    input_path = ("lmdb->transformer->device-resident dataset"
                  if runner._dataset is not None
                  else "host feed per step")
    runner.step(CHUNK, chunk=CHUNK)  # compile + warmup
    jax.block_until_ready(runner.params)
    setup_s = time.perf_counter() - t_setup

    # span tracing starts AFTER warmup: the phase breakdown attributes
    # the TIMED windows, not the compile/decode cold start (which the
    # setup record already splits)
    tracer = runner.enable_tracing() if TRACE else None

    windows = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        runner.step(STEPS, chunk=CHUNK)
        jax.block_until_ready(runner.params)
        windows.append(time.perf_counter() - t0)
    dt = min(windows)
    # the setup record is taken AFTER the timed windows so its pipeline
    # accounting covers the whole run's chunks, not just the warmup
    setup_rec = runner.setup_record(setup_s)
    phase_extra = {}
    if tracer is not None:
        # span-derived attribution of the timed windows' HOST seconds
        # (observe/spans.py bench_phase_breakdown documents the
        # bucket definitions; checkpoint/prefetch are zero on this
        # bench — rows share one shape)
        from rram_caffe_simulation_tpu.observe import spans as obs_spans
        phase_extra = {"phase_breakdown":
                       obs_spans.bench_phase_breakdown(tracer.events())}
    runner.close()

    # chips = the devices the sweep actually ran on: the whole mesh
    # when config-sharded, every visible device on the classic row
    n_chips = (len(runner.mesh.devices.ravel())
               if args.mesh else len(jax.devices()))
    img_s_chip = N_CONFIGS * BATCH * STEPS / dt / n_chips
    # aggregate across the mesh: the whole runner's throughput (the
    # per-chip figure divides by chips)
    configs_per_hour = N_CONFIGS * STEPS / dt * 3600.0 / 5000.0
    # (configs/hour normalized to a 5k-iteration CIFAR-quick training run)
    # HBM-floor accounting (ROADMAP item 3): estimated resident-state
    # bytes one sweep iteration moves, and the bandwidth the min window
    # achieved against that floor — the trajectory r06+ tracks as the
    # packed/quantized engines shrink bytes-per-step
    # bytes_per_step_est is already the PER-CHIP resident share (the
    # runner divides config-sharded leaves by the shard count), so the
    # achieved-bandwidth figure must NOT divide by chips again
    bytes_step = setup_rec.get("bytes_per_step_est") or 0
    achieved_gb_s = bytes_step * STEPS / dt / 1e9

    extra_mesh = {}
    if args.mesh:
        # the pod-scale row (chips > 1): the config axis sharded over
        # the mesh as ONE jitted program — aggregate configs/hour is
        # the scaling headline (acceptance: >= 0.8 * N x single-chip)
        extra_mesh = {
            "mesh": dict(runner.mesh.shape),
            "configs_per_hour_aggregate": round(configs_per_hour, 2),
            "configs_per_hour_per_chip": round(
                configs_per_hour / n_chips, 2),
        }

    print(json.dumps({
        "metric": "images/sec/chip under RRAM noise (CIFAR-10-quick, "
                  f"{N_CONFIGS}-config Monte-Carlo sweep, LMDB input"
                  + (f", {DTYPE} compute" if DTYPE else "")
                  + (f", config-sharded over {n_chips} chips"
                     if args.mesh else "") + ")",
        "value": round(img_s_chip, 1),
        "unit": "img/s/chip",
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 2),
        "extra": {
            "fault_configs_swept_per_hour_5k_iters":
                round(configs_per_hour, 2),
            **extra_mesh,
            "input_path": input_path,
            "setup_seconds_incl_lmdb_decode_and_compile":
                round(setup_s, 1),
            # the cold-start split (observe `setup` record shape):
            # decode/compile may overlap (precompile_chunk), cache
            # states hit|miss|partial|disabled per component
            "decode_seconds": setup_rec["decode_seconds"],
            "compile_seconds": setup_rec["compile_seconds"],
            "cache": setup_rec["cache"],
            # async dispatch pipeline accounting (observe `setup`
            # record "pipeline" shape): depth, chunks dispatched, and
            # the dispatcher's host-blocked seconds across them
            "pipeline": setup_rec.get("pipeline", {}),
            # the bytes-per-step attack surface (ISSUE 7): which
            # crossbar engine / fault-state banks / ADC-grid policy ran,
            # the resident-state bytes one iteration moves, and the
            # bandwidth the timed window sustained against that floor.
            # `engine` is ALWAYS the resolved engine from the runner —
            # a mesh row can never claim a kernel that actually ran
            # pure JAX; when the request fell back, the schema-
            # validated reason rides along (ISSUE 13)
            "engine": runner.engine_resolved,
            **({"engine_fallback_reason": runner.engine_fallback_reason}
               if runner.engine_fallback_reason else {}),
            # the fused ApplyUpdate+Fail kernel tail (fault/fused.py):
            # True when the packed banks were read-modified-written in
            # VMEM instead of streamed as separate HBM ops
            "fused_epilogue": runner.fused_epilogue_resolved,
            "fault_state_format": setup_rec.get("fault_state_format",
                                                "f32"),
            "dtype_policy": DTYPE_POLICY or "off",
            "bytes_per_step_est": bytes_step,
            "achieved_bandwidth_gb_s_per_chip": round(achieved_gb_s, 2),
            **phase_extra,
            "steps_timed": STEPS, "batch": BATCH, "chunk": CHUNK,
            "n_configs": N_CONFIGS, "chips": n_chips,
            "seconds": round(dt, 3),
            "repeats": repeats,
            "window_seconds": [round(w, 3) for w in windows],
            # companion measurements live in-repo (ImageNet-class
            # training rows, the measured 1000-config north star):
            "see_also": ["RESULTS.md", "examples/bench_train.py",
                         "examples/gaussian_failure/logs/"
                         "sweep_1000_measured.log"],
        },
    }))


if __name__ == "__main__":
    main()

"""Headline benchmark: images/sec/chip under RRAM noise (BASELINE.json
metric), measured on CIFAR-10-quick training with the Gaussian fault engine
fused into every step, Monte-Carlo fault-config axis vmapped on-chip.

Counting: each of the N simultaneously-trained fault configs consumes the
shared batch every step (the reference trains one config per GPU process —
run_different_mean.sh — so per-config images are the comparable unit of
work). vs_baseline divides by the reference's best published training
throughput, 267 img/s (CaffeNet w/ cuDNN on K40,
docs/performance_hardware.md:23-25).

Prints exactly ONE JSON line on stdout.
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BASELINE_IMG_S = 267.0  # reference: CaffeNet+cuDNN on K40

BATCH = 100          # matches the fault engine's per-write decrement
N_CONFIGS = int(os.environ.get("BENCH_CONFIGS", "64"))
STEPS = int(os.environ.get("BENCH_STEPS", "30"))


def main():
    import jax
    import jax.numpy as jnp
    from google.protobuf import text_format

    from rram_caffe_simulation_tpu.proto import pb
    from rram_caffe_simulation_tpu.solver import Solver
    from rram_caffe_simulation_tpu.parallel import SweepRunner
    from rram_caffe_simulation_tpu.utils.io import read_net_param

    sp = pb.SolverParameter()
    sp.net_param.CopyFrom(read_net_param(os.path.join(
        REPO, "models", "cifar10_quick",
        "cifar10_quick_train_test.prototxt")))
    sp.base_lr = 0.001
    sp.lr_policy = "fixed"
    sp.momentum = 0.9
    sp.weight_decay = 0.004
    sp.type = "SGD"
    sp.max_iter = 10 ** 9
    sp.display = 0
    sp.random_seed = 1
    sp.snapshot_prefix = "/tmp/bench"
    # reference RRAM operating point (usage.md; solvers/
    # cifar10_vgg11_template.prototxt:36-39): lifetimes ~ N(1e8, 3e7)
    sp.failure_pattern.type = "gaussian"
    sp.failure_pattern.mean = 1e8
    sp.failure_pattern.std = 3e7

    rng = np.random.RandomState(0)
    batch = {"data": rng.randn(BATCH, 3, 32, 32).astype(np.float32),
             "label": rng.randint(0, 10, BATCH).astype(np.int32)}
    solver = Solver(sp, train_feed=lambda: batch)
    runner = SweepRunner(solver, n_configs=N_CONFIGS)

    runner.step(1)  # compile + warmup
    jax.block_until_ready(runner.params)

    t0 = time.perf_counter()
    runner.step(STEPS)
    jax.block_until_ready(runner.params)
    dt = time.perf_counter() - t0

    n_chips = len(jax.devices())
    img_s_chip = N_CONFIGS * BATCH * STEPS / dt / n_chips
    configs_per_hour = N_CONFIGS * STEPS / dt * 3600.0 / 5000.0
    # (configs/hour normalized to a 5k-iteration CIFAR-quick training run)

    print(json.dumps({
        "metric": "images/sec/chip under RRAM noise (CIFAR-10-quick, "
                  f"{N_CONFIGS}-config Monte-Carlo sweep)",
        "value": round(img_s_chip, 1),
        "unit": "img/s/chip",
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 2),
        "extra": {
            "fault_configs_swept_per_hour_5k_iters":
                round(configs_per_hour, 2),
            "steps_timed": STEPS, "batch": BATCH,
            "n_configs": N_CONFIGS, "chips": n_chips,
            "seconds": round(dt, 3),
        },
    }))


if __name__ == "__main__":
    main()
